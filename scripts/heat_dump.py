#!/usr/bin/env python3
"""Live-cluster data-plane skew report from the eg_heat profiler.

Scrapes every shard's heat dump (kHeat opcode: hot-vertex top-K table,
count-min totals, per-op/per-conn ids ledger) and prints the skew
measurements ROADMAP item 5 (locality-aware sharding + hot-vertex
caching) will be judged against:

  * per shard: the top-K hot-vertex table with space-saving error
    bounds, the share of the shard's access stream the top-K absorbs,
    and a Zipf fit of the tail exponent (log count ~ -alpha log rank);
  * with --probe N: the client-side view after N training-shaped probe
    steps (sample_node -> 2-hop fanout -> dense features) — per-op
    ids_requested / ids_after_dedup / cache_hits / ids_on_wire ledger,
    mean shards touched per call, bytes per shard, and the MEASURED
    cross-shard edge-cut under the current hash sharding (fraction of
    sampled (parent, child) hops whose endpoints live on different
    shards — the number a locality-aware partitioner must beat);
  * the projected FREQUENCY-AWARE CACHE hit-rate ceiling at the
    configured capacity: if the cache pinned the C hottest ids, every
    access after an id's first would hit — computed from the tracked
    top-K and Zipf-extrapolated beyond it, next to the measured hit
    rate of the current FIFO cache.

Usage:
    python scripts/heat_dump.py --registry /shared/reg
    python scripts/heat_dump.py --shards h1:9001,h2:9001 --probe 8
    python scripts/heat_dump.py --registry tcp://host:9100 --json
    python scripts/heat_dump.py --smoke     # self-contained 2-shard
                                            # drill (verify.sh gate)

See OBSERVABILITY.md "Data-plane heat" for the triage runbook and
PERF.md "Data-plane heat" for the recorded reddit_heavytail baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def probe_workload(graph, steps: int, batch: int = 64, fanouts=(5, 5),
                   feature_dim: int = 8):
    """Run the training-shaped workload (roots -> 2-hop fanout -> dense
    features over the frontier) and measure the sharding edge-cut
    directly from the sampled hops: the fraction of (parent, child)
    pairs whose ids route to different shards — through the client's
    ACTUAL routing (placement map when loaded, hash otherwise), so a
    locality-aware cluster is measured by the routing it uses."""
    def shard_of(ids):
        return graph.shard_of(np.asarray(ids))

    cross = 0
    total = 0
    f1, f2 = fanouts
    for _ in range(steps):
        roots = graph.sample_node(batch, -1)
        hop_ids, _, _ = graph.sample_fanout(
            roots, [[0], [0]] if graph.edge_type_num == 1
            else [[0, 1], [0, 1]], [f1, f2],
        )
        frontier = np.concatenate(hop_ids)
        graph.get_dense_feature(frontier, [0], [feature_dim])
        for parents, children, fan in (
            (hop_ids[0], hop_ids[1], f1),
            (hop_ids[1], hop_ids[2], f2),
        ):
            ps = np.repeat(shard_of(parents), fan)
            cs = shard_of(children)
            cross += int((ps != cs).sum())
            total += len(cs)
    return {"hops_sampled": total, "cross_shard_hops": cross,
            "placement_routed": bool(graph.has_placement),
            "edge_cut": round(cross / total, 4) if total else 0.0}


def build_report(graph, probe: dict | None, cache_mb: int,
                 row_bytes: int) -> dict:
    from euler_tpu import counters
    from euler_tpu import heat as H

    report: dict = {"num_shards": graph.num_shards, "shards": []}
    combined_total = 0
    for s in range(graph.num_shards):
        d = H.heat_json(graph, s)
        top = d["topk"]["server"]
        total = d["sketch"]["total"]["server"]
        combined_total += total
        report["shards"].append({
            "shard": s,
            "ids_total": total,
            "topk": top,
            "topk_share": round(H.topk_share(d, "server"), 4),
            "zipf": H.zipf_fit(top),
            "conns": d["conns"],
        })

    # client-side view (this process): fan-out ledger + cache ceiling
    local = H.heat_json()
    client_top = local["topk"]["client"]
    client_total = local["sketch"]["total"]["client"]
    report["client"] = {
        "ids_total": client_total,
        "topk_share": round(H.topk_share(local, "client"), 4),
        "zipf": H.zipf_fit(client_top),
        "fanout": local["fanout"],
        "shard_bytes": local["shard_bytes"],
        "cache_class": local["cache_class"],
    }
    if probe is not None:
        report["edge_cut"] = probe

    # projected frequency-aware cache ceiling at the configured budget
    capacity_rows = (cache_mb << 20) // max(row_bytes, 1)
    ceiling = H.cache_hit_ceiling(client_top, client_total, capacity_rows)
    if ceiling:
        ceiling["cache_mb"] = cache_mb
        ceiling["row_bytes"] = row_bytes
        ctr = counters()
        probes = ctr["cache_hits"] + ctr["cache_misses"]
        if probes:
            ceiling["measured_hit_rate"] = round(
                ctr["cache_hits"] / probes, 4
            )
            # older key kept so recorded baselines keep parsing
            ceiling["measured_fifo_hit_rate"] = ceiling["measured_hit_rate"]
        report["cache_ceiling"] = ceiling

    # one flat gate-friendly block: the numbers a locality A/B script
    # compares (edge-cut, cache hit rate, ids on wire) without walking
    # the nested report
    ctr = counters()
    feat_probes = ctr["cache_hits"] + ctr["cache_misses"]
    nbr_probes = ctr["nbr_cache_hits"] + ctr["nbr_cache_misses"]
    on_wire = sum(f["ids_on_wire"] for f in local["fanout"].values())
    report["summary"] = {
        "placement_routed": bool(getattr(graph, "has_placement", False)),
        "edge_cut": probe["edge_cut"] if probe else None,
        "topk_share": report["client"]["topk_share"],
        "ids_on_wire": on_wire,
        "feature_cache_hit_rate": (
            round(ctr["cache_hits"] / feat_probes, 4) if feat_probes
            else None
        ),
        "nbr_cache_hit_rate": (
            round(ctr["nbr_cache_hits"] / nbr_probes, 4) if nbr_probes
            else None
        ),
        "cache_admit_rejects": ctr["cache_admit_rejects"],
        "projected_hit_ceiling": (
            report["cache_ceiling"]["projected_hit_rate"]
            if "cache_ceiling" in report else None
        ),
    }
    return report


def print_report(report: dict, top_n: int = 10) -> None:
    for sh in report["shards"]:
        z = sh["zipf"]
        zs = (f"zipf alpha {z['alpha']} (r2 {z['r2']})" if z
              else "zipf fit n/a")
        print(f"== shard {sh['shard']} == ids {sh['ids_total']}  "
              f"top-{len(sh['topk'])} share {sh['topk_share']:.1%}  {zs}")
        if sh["topk"]:
            print(f"  {'rank':>4s} {'id':>12s} {'count':>10s} {'err':>7s}")
            for rank, e in enumerate(sh["topk"][:top_n], 1):
                print(f"  {rank:4d} {e['id']:12d} {e['count']:10d} "
                      f"{e['err']:7d}")
        if sh["conns"]:
            print(f"  conns: {sh['conns']}")
    c = report["client"]
    print(f"== client == ids {c['ids_total']}  top-K share "
          f"{c['topk_share']:.1%}")
    for op, f in sorted(c["fanout"].items()):
        mean_shards = (f["shards_touched"] / f["calls"]) if f["calls"] else 0
        print(f"  {op:20s} calls {f['calls']:6d} requested "
              f"{f['ids_requested']:8d} deduped {f['ids_deduped']:8d} "
              f"cache_hits {f['cache_hits']:8d} on_wire "
              f"{f['ids_on_wire']:8d} shards/call {mean_shards:.2f}")
    if "edge_cut" in report:
        e = report["edge_cut"]
        routing = ("placement-routed" if e.get("placement_routed")
                   else "hash-sharding")
        print(f"{routing} edge-cut: {e['edge_cut']:.1%} of "
              f"{e['hops_sampled']} sampled hops crossed shards")
    if "cache_ceiling" in report:
        ce = report["cache_ceiling"]
        line = (f"frequency-aware cache ceiling @ {ce['cache_mb']} MB "
                f"({ce['capacity_rows']} rows): "
                f"{ce['projected_hit_rate']:.1%} projected hit rate")
        if "measured_fifo_hit_rate" in ce:
            line += f" (measured FIFO: {ce['measured_fifo_hit_rate']:.1%})"
        print(line)


def run_smoke() -> int:
    """Self-contained drill: tiny power-law 2-shard cluster, probe
    workload, then assert the report's invariants (verify.sh gate)."""
    import shutil
    import tempfile

    import euler_tpu
    from euler_tpu.graph.service import GraphService
    from scripts.remote_bench import build_powerlaw_fixture

    tmp = tempfile.mkdtemp(prefix="euler_heat_smoke_")
    svcs = []
    try:
        data = os.path.join(tmp, "data")
        os.makedirs(data)
        build_powerlaw_fixture(data, 300, 10, 8)
        svcs = [GraphService(data, s, 2) for s in range(2)]
        g = euler_tpu.Graph(
            mode="remote", shards=[s.address for s in svcs],
            retries=2, timeout_ms=2000,
        )
        try:
            euler_tpu.telemetry_reset()
            euler_tpu.reset_counters()
            probe = probe_workload(g, steps=4, batch=32, fanouts=(5, 5))
            report = build_report(g, probe, cache_mb=64, row_bytes=128)
            print_report(report)
            assert len(report["shards"]) == 2, report
            for sh in report["shards"]:
                assert sh["ids_total"] > 0, sh
                assert sh["topk"], sh
                assert 0.0 < sh["topk_share"] <= 1.0, sh
                assert sh["zipf"] and sh["zipf"]["alpha"] > 0, sh
            # the power-law fixture routes most mass to a few hubs —
            # the measured hash-sharding edge-cut must be substantial
            assert 0.0 < report["edge_cut"]["edge_cut"] <= 1.0, report
            # ids ledger identity as seen by the heat surface
            f = report["client"]["fanout"]["dense_feature"]
            assert f["ids_on_wire"] == (f["ids_requested"]
                                        - f["ids_deduped"]
                                        - f["cache_hits"]), f
            assert "cache_ceiling" in report, report
            ce = report["cache_ceiling"]
            assert 0.0 < ce["projected_hit_rate"] <= 1.0, ce
            print("heat_dump smoke: OK")
            return 0
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run_ab_smoke() -> int:
    """Locality A/B drill (the verify.sh gate): partition the SAME
    power-law fixture twice — hash vs degree-aware placement — run the
    probe workload against a live 2-shard cluster of each, and assert
    the placement edge-cut comes out strictly below hash. The counters
    and heat tables are process-global, so each leg resets them."""
    import shutil
    import tempfile

    import euler_tpu
    from euler_tpu.graph import native
    from euler_tpu.graph.convert import convert_dicts
    from euler_tpu.graph.service import GraphService
    from scripts.remote_bench import PL_META, powerlaw_fixture_nodes

    tmp = tempfile.mkdtemp(prefix="euler_locality_ab_")
    try:
        # one node set, two partitionings of it
        nodes = powerlaw_fixture_nodes(400, 10, 8, alpha=1.4)
        meta = PL_META
        results = {}
        for mode in ("hash", "degree"):
            data = os.path.join(tmp, mode)
            os.makedirs(data)
            convert_dicts(nodes, meta, data + "/part", num_partitions=4,
                          placement=mode)
            svcs = [GraphService(data, s, 2) for s in range(2)]
            try:
                g = euler_tpu.Graph(
                    mode="remote", shards=[s.address for s in svcs],
                    retries=2, timeout_ms=3000,
                )
                try:
                    euler_tpu.telemetry_reset()
                    native.reset_counters()
                    probe = probe_workload(g, steps=4, batch=32,
                                           fanouts=(5, 5))
                    report = build_report(g, probe, cache_mb=64,
                                          row_bytes=128)
                    results[mode] = report["summary"]
                finally:
                    g.close()
            finally:
                for s in svcs:
                    s.stop()

        h, d = results["hash"], results["degree"]
        print(f"hash    edge-cut {h['edge_cut']:.1%}  ids_on_wire "
              f"{h['ids_on_wire']}  placement_routed "
              f"{h['placement_routed']}")
        print(f"degree  edge-cut {d['edge_cut']:.1%}  ids_on_wire "
              f"{d['ids_on_wire']}  placement_routed "
              f"{d['placement_routed']}")
        assert not h["placement_routed"], h
        assert d["placement_routed"], d
        # the gate: locality-aware placement must STRICTLY beat hash on
        # the same graph, same workload shape
        assert d["edge_cut"] < h["edge_cut"], (
            f"placement edge-cut {d['edge_cut']} not below hash "
            f"{h['edge_cut']}"
        )
        print("locality A/B smoke: OK")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--registry", default="", help=(
        "registry dir or tcp://host:port the cluster registered with"))
    ap.add_argument("--shards", default="", help=(
        "explicit comma-separated host:port shard list"))
    ap.add_argument("--timeout_ms", type=int, default=3000)
    ap.add_argument("--probe", type=int, default=0, metavar="N", help=(
        "run N training-shaped probe steps through this client first, "
        "so the client-side fan-out ledger and the measured edge-cut "
        "exist (0 = passive: server-side tables only)"))
    ap.add_argument("--cache_mb", type=int, default=64, help=(
        "cache budget for the frequency-aware ceiling projection "
        "(matches the feature_cache_mb default)"))
    ap.add_argument("--row_bytes", type=int, default=2504, help=(
        "bytes per cached feature row for the ceiling projection "
        "(default: reddit-shaped 602 floats + entry overhead)"))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: one JSON report")
    ap.add_argument("--smoke", action="store_true", help=(
        "spin a tiny local 2-shard cluster and assert the report "
        "(the verify.sh gate)"))
    ap.add_argument("--ab-smoke", action="store_true", help=(
        "locality A/B drill: partition one power-law fixture hash vs "
        "degree-aware, probe both live 2-shard clusters, assert the "
        "placement edge-cut strictly below hash (the verify.sh gate)"))
    args = ap.parse_args()

    if args.smoke:
        return run_smoke()
    if args.ab_smoke:
        return run_ab_smoke()
    if not args.registry and not args.shards:
        ap.error("need --registry or --shards (or --smoke)")

    import euler_tpu

    g = euler_tpu.Graph(
        mode="remote",
        registry=args.registry or None,
        shards=args.shards.split(",") if args.shards else None,
        retries=2,
        timeout_ms=args.timeout_ms,
        rediscover_ms=0,
    )
    try:
        probe = probe_workload(g, args.probe) if args.probe > 0 else None
        report = build_report(g, probe, args.cache_mb, args.row_bytes)
        if args.json:
            print(json.dumps(report))
        else:
            print_report(report)
    finally:
        g.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
