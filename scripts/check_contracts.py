#!/usr/bin/env python3
"""Cross-layer contract analyzer for the native graph engine.

check_native.py (whose stripping/brace-matching/escape core this reuses)
lints single-file *shapes*; the drift it cannot see is CROSS-LAYER — an
ABI signature, opcode slot, counter name, config key, or lock protocol
that silently disagrees between eg_capi.cc, native.py, eg_wire.h,
Service::Dispatch, eg_stats.h, and the docs. Each pass below parses both
sides of one such contract and diffs them structurally (no libclang —
every surface involved is regular enough for a line/brace-aware scan).

Passes (each individually testable, see tests/test_contracts.py):

  abi     every `extern "C"` function in eg_capi.cc/eg_api.h has a
          ctypes `_sig(L.name, ...)` binding in euler_tpu/graph/native.py
          and vice versa, with matching arity and per-slot type CLASS
          (pointer vs scalar vs void) — an arity or class mismatch is a
          silent stack/register misread at call time, not an error.
  wire    `enum WireOp` (eg_wire.h): opcode values unique;
          kHistOpSlots == max opcode + 1 and kWireOpNames covers every
          slot (eg_telemetry.h); every opcode has BOTH a Service::Dispatch
          `case` (eg_service.cc) and a client-side `U8(kOp)` encoder
          (eg_remote.cc) — a dispatch-only op is dead server code, an
          encoder-only op is a guaranteed runtime error.
  ledger  counter/stat name tables (eg_stats.h): enum count == name-table
          count, names unique; every counter documented in the FAULTS.md
          glossary and every glossary row backed by a real counter; the
          counter names quoted in euler_tpu.counters()' docstring exist.
  config  config keys parsed by eg_remote.cc / eg_admission.cc vs the
          README config-key tables, graph.py's `known` kwarg set and
          run_loop.py flags — an undocumented key is invisible to
          operators, a documented-but-unparsed key is a silent no-op.
  lock    every field annotated `EG_GUARDED_BY(mu)` (eg_common.h) is only
          touched inside a scope holding an RAII guard on that mutex
          (std::lock_guard / unique_lock / scoped_lock), including
          wait-predicate lambdas under an enclosing unique_lock;
          constructors/destructors are exempt (exclusive access).
  artifacts  no tracked `.o`/`.so`/`.a`/`.flavor` build artifacts; no
          orphan objects whose source is gone (the stale-object
          incident ROADMAP recorded — an eg_epoch.o outliving its
          source; eg_epoch.cc is real source now, so only a
          SOURCELESS object is an orphan); .gitignore covers the
          artifact set.

Escapes: same grammar as check_native.py —

    // eg-lint: allow(<rule>) <reason>      (C++)
    # eg-lint: allow(<rule>) <reason>       (Python)

on the offending line or the comment run directly above; the reason is
mandatory. Rule names here: abi-parity, wire-parity, ledger-parity,
config-parity, guarded-by, artifact-hygiene. Markdown sides (README,
FAULTS.md) are NOT waivable — fix the doc. A contract escape that no
longer suppresses anything is itself flagged stale.

Usage:
    python scripts/check_contracts.py                 # all passes
    python scripts/check_contracts.py --passes lock,wire
    python scripts/check_contracts.py --list-passes

Exit codes: 0 clean, 1 violations found, 2 bad invocation / missing file.
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import re
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))


def _load_check_native():
    spec = importlib.util.spec_from_file_location(
        "check_native", os.path.join(_HERE, "check_native.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_native", mod)  # dataclasses needs the entry
    spec.loader.exec_module(mod)
    return mod


cn = _load_check_native()
Violation = cn.Violation

PASSES = {
    "abi": "extern \"C\" surface vs ctypes _sig bindings (name/arity/type class)",
    "wire": "WireOp table: unique opcodes, slot count, dispatch + encoder coverage",
    "ledger": "counter/stat name tables vs FAULTS.md glossary vs Python docstring",
    "config": "config keys parsed by native/Python vs README tables/run_loop flags",
    "lock": "EG_GUARDED_BY(mu) fields touched only under their RAII guard",
    "artifacts": "tracked/orphan build artifacts + .gitignore coverage",
}
RULE_OF_PASS = {
    "abi": "abi-parity",
    "wire": "wire-parity",
    "ledger": "ledger-parity",
    "config": "config-parity",
    "lock": "guarded-by",
    "artifacts": "artifact-hygiene",
}
CONTRACT_RULES = set(RULE_OF_PASS.values())

PY_ALLOW_RE = re.compile(r"#\s*eg-lint:\s*allow\(([\w-]+)\)\s*(.*)")


# ---------------------------------------------------------------------------
# Shared infrastructure: per-file parse cache + escape-aware reporter
# ---------------------------------------------------------------------------


def strip_comments_keep_strings(text: str) -> str:
    """Like check_native.strip_comments_and_strings but string literal
    CONTENT survives (the config/ledger passes diff quoted names)."""
    out = []
    i, n = 0, len(text)
    state = "code"
    while i < n:
        c = text[i]
        nxt = text[i + 1] if i + 1 < n else ""
        if state == "code":
            if c == "/" and nxt == "/":
                state = "line_comment"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                state = "block_comment"
                out.append("  ")
                i += 2
                continue
            if c == '"':
                state = "string"
            elif c == "'":
                state = "char"
            out.append(c)
        elif state == "line_comment":
            if c == "\n":
                state = "code"
            out.append("\n" if c == "\n" else " ")
        elif state == "block_comment":
            if c == "*" and nxt == "/":
                out.append("  ")
                i += 2
                state = "code"
                continue
            out.append("\n" if c == "\n" else " ")
        else:  # string | char
            if c == "\\":
                out.append(text[i : i + 2])
                i += 2
                continue
            if (state == "string" and c == '"') or (state == "char" and c == "'"):
                state = "code"
            out.append(c)
        i += 1
    return "".join(out)


class SourceFile:
    """One parsed file: raw text, stripped variants, allows, blocks."""

    def __init__(self, path: str):
        self.path = path
        with open(path, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        if path.endswith(".py"):
            self.code = self.text  # Python: ast does the real parsing
            self.allows = {}
            for ln, line in enumerate(self.text.split("\n"), 1):
                m = PY_ALLOW_RE.search(line)
                if m:
                    self.allows.setdefault(ln, []).append(
                        (m.group(1), m.group(2).strip())
                    )
            self.blocks = []
            self.code_strings = self.text
        else:
            self.code, self.allows = cn.strip_comments_and_strings(self.text)
            self.blocks = cn.extract_blocks(self.code)
            self.code_strings = strip_comments_keep_strings(self.text)
        self.lines = self.code.split("\n")


class Checker:
    """Violation collector with check_native's escape semantics."""

    def __init__(self, root: str):
        self.root = root
        self.violations: list[Violation] = []
        self._files: dict[str, SourceFile] = {}
        self.used_allows: set[tuple[str, int, str]] = set()

    def file(self, *rel) -> SourceFile:
        path = os.path.join(self.root, *rel)
        if path not in self._files:
            self._files[path] = SourceFile(path)
        return self._files[path]

    def native(self, name: str) -> SourceFile:
        return self.file("euler_tpu", "graph", "_native", name)

    def rel(self, path: str) -> str:
        return os.path.relpath(path, self.root)

    def _try_allow(self, sf: SourceFile, cand: int, rule: str) -> bool:
        for arule, reason in sf.allows.get(cand, []):
            if arule == rule:
                self.used_allows.add((sf.path, cand, arule))
                if not reason:
                    self.violations.append(
                        Violation(
                            self.rel(sf.path),
                            cand,
                            "allow-escape",
                            f"allow({rule}) escape has no reason — justify "
                            "the exception so it is visible in review",
                        )
                    )
                return True
        return False

    def report(self, sf: SourceFile | None, line: int, rule: str, message: str):
        if sf is not None:
            if self._try_allow(sf, line, rule):
                return
            cand = line - 1
            lines = sf.text.split("\n")
            while cand >= 1:
                if self._try_allow(sf, cand, rule):
                    return
                if cand <= len(lines) and sf.lines[
                    min(cand, len(sf.lines)) - 1
                ].strip():
                    break  # real code above without a matching allow
                cand -= 1
            path = self.rel(sf.path)
        else:
            path = "."
        self.violations.append(Violation(path, line, rule, message))

    def audit_stale_escapes(self, rules=None):
        """A contract escape that suppressed nothing is itself stale.
        Only escapes for `rules` (default: all contract rules) are
        audited — an escape cannot be stale if its pass never ran."""
        audited = CONTRACT_RULES if rules is None else set(rules)
        for sf in self._files.values():
            for ln, entries in sf.allows.items():
                for arule, _ in entries:
                    if arule not in audited:
                        continue  # check_native audits its own rules
                    if (sf.path, ln, arule) not in self.used_allows:
                        self.violations.append(
                            Violation(
                                self.rel(sf.path),
                                ln,
                                "allow-escape",
                                f"stale escape: allow({arule}) suppresses "
                                "nothing on this line any more — delete it",
                            )
                        )


def line_of(code: str, off: int) -> int:
    return code.count("\n", 0, off) + 1


# ---------------------------------------------------------------------------
# Pass: abi — extern "C" in eg_capi.cc vs _sig bindings in native.py
# ---------------------------------------------------------------------------

CAPI_FN_RE = re.compile(
    r"(?:^|[;{}])\s*((?:\w+[\s*&]+)+)(eg_\w+)\s*\(([^)]*)\)\s*\{"
)


def parse_capi_functions(chk: Checker):
    """(name -> (line, ret_class, [arg_class...])) from extern "C" blocks."""
    out = {}
    for fname in ("eg_capi.cc", "eg_api.h"):
        sf = chk.native(fname)
        spans = [
            (b.start, b.end if b.end >= 0 else len(sf.code))
            for b in sf.blocks
            if b.kind == "extern"
        ]
        for lo, hi in spans:
            seg = sf.code[lo:hi]
            for m in CAPI_FN_RE.finditer(seg):
                ret, name, params = m.group(1), m.group(2), m.group(3)
                out[name] = (
                    sf,
                    line_of(sf.code, lo + m.start(2)),
                    c_type_class(ret),
                    [c_type_class(p) for p in split_c_params(params)],
                )
    return out


def split_c_params(params: str) -> list[str]:
    s = " ".join(params.split())
    if not s or s == "void":
        return []
    return [p.strip() for p in s.split(",")]


def c_type_class(decl: str) -> str:
    if "*" in decl:
        return "ptr"
    if re.fullmatch(r"\s*void\s*", decl):
        return "void"
    return "scalar"


def parse_py_bindings(chk: Checker):
    """(name -> (line, ret_class, [arg_class...])) from _sig calls."""
    sf = chk.file("euler_tpu", "graph", "native.py")
    tree = ast.parse(sf.text)
    aliases: dict[str, str] = {}
    out = {}

    def classify(node) -> str:
        if isinstance(node, ast.Constant) and node.value is None:
            return "void"
        if isinstance(node, ast.Name):
            return aliases.get(node.id, "scalar")
        if isinstance(node, ast.Attribute):
            if node.attr in ("c_void_p", "c_char_p"):
                return "ptr"
            return "scalar"
        if isinstance(node, ast.Call):
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
            if fname == "POINTER":
                return "ptr"
        return "scalar"

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            aliases[node.targets[0].id] = classify(node.value)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_sig"
            and len(node.args) == 3
            and isinstance(node.args[0], ast.Attribute)
        ):
            name = node.args[0].attr
            args = node.args[2]
            argcls = (
                [classify(a) for a in args.elts]
                if isinstance(args, ast.List)
                else None
            )
            out[name] = (sf, node.lineno, classify(node.args[1]), argcls)
    return out


def pass_abi(chk: Checker):
    native = parse_capi_functions(chk)
    py = parse_py_bindings(chk)
    for name, (sf, ln, ret, argcls) in sorted(native.items()):
        if name not in py:
            chk.report(
                sf, ln, "abi-parity",
                f"extern \"C\" `{name}` has no ctypes binding in native.py — "
                "an unbound symbol is dead ABI surface (or a binding was "
                "renamed without its symbol)",
            )
    for name, (sf, ln, ret, argcls) in sorted(py.items()):
        if name not in native:
            chk.report(
                sf, ln, "abi-parity",
                f"_sig(L.{name}, ...) binds a symbol that no extern \"C\" "
                "block defines — this raises AttributeError at lib() time "
                "(or calls a stale symbol if an old .so is loaded)",
            )
            continue
        nsf, nln, nret, nargs = native[name]
        if argcls is None:
            chk.report(
                sf, ln, "abi-parity",
                f"_sig(L.{name}, ...) argtypes is not a literal list — the "
                "analyzer cannot prove the call frame matches "
                f"{chk.rel(nsf.path)}:{nln}",
            )
            continue
        if len(argcls) != len(nargs):
            chk.report(
                sf, ln, "abi-parity",
                f"_sig(L.{name}) declares {len(argcls)} argument(s) but the "
                f"native definition at {chk.rel(nsf.path)}:{nln} takes "
                f"{len(nargs)} — an arity mismatch misreads the call frame "
                "silently",
            )
            continue
        for i, (pc, ncl) in enumerate(zip(argcls, nargs)):
            if pc != ncl:
                chk.report(
                    sf, ln, "abi-parity",
                    f"_sig(L.{name}) argument {i} is {pc} but the native "
                    f"definition at {chk.rel(nsf.path)}:{nln} takes {ncl} — "
                    "a pointer/scalar class mismatch corrupts the call frame",
                )
        if ret != nret:
            chk.report(
                sf, ln, "abi-parity",
                f"_sig(L.{name}) restype class is {ret} but the native "
                f"definition at {chk.rel(nsf.path)}:{nln} returns {nret}",
            )


# ---------------------------------------------------------------------------
# Pass: wire — WireOp enum vs slots vs dispatch vs client encoders
# ---------------------------------------------------------------------------


def parse_enum(sf: SourceFile, enum_name: str):
    """[(name, value, line)] for a plain C++ enum (explicit or implicit
    values); None if the enum is not found."""
    m = re.search(
        r"enum\s+(?:class\s+)?%s\b[^{]*\{" % re.escape(enum_name), sf.code
    )
    if not m:
        return None
    body_start = m.end()
    depth = 1
    i = body_start
    while i < len(sf.code) and depth:
        if sf.code[i] == "{":
            depth += 1
        elif sf.code[i] == "}":
            depth -= 1
        i += 1
    body = sf.code[body_start : i - 1]
    entries = []
    nxt = 0
    for item in body.split(","):
        em = re.search(r"(\w+)\s*(?:=\s*([\w<>x]+))?", item)
        if not em or not em.group(1):
            continue
        name = em.group(1)
        if em.group(2) is not None:
            try:
                val = int(em.group(2), 0)
            except ValueError:
                continue  # expression value: out of scope
        else:
            val = nxt
        nxt = val + 1
        entries.append((name, val, line_of(sf.code, body_start + body.find(item))))
    return entries


def pass_wire(chk: Checker):
    wire = chk.native("eg_wire.h")
    ops = parse_enum(wire, "WireOp")
    if not ops:
        chk.report(wire, 1, "wire-parity", "enum WireOp not found in eg_wire.h")
        return
    seen: dict[int, str] = {}
    for name, val, ln in ops:
        if val in seen:
            chk.report(
                wire, ln, "wire-parity",
                f"opcode value {val} of `{name}` duplicates `{seen[val]}` — "
                "two ops on one wire byte dispatch to whichever came first",
            )
        else:
            seen[val] = name
    max_op = max(v for _, v, _ in ops)

    tele = chk.native("eg_telemetry.h")
    sm = re.search(r"kHistOpSlots\s*=\s*(\d+)", tele.code)
    if not sm:
        chk.report(tele, 1, "wire-parity", "kHistOpSlots not found")
    else:
        slots = int(sm.group(1))
        if slots != max_op + 1:
            chk.report(
                tele, line_of(tele.code, sm.start()), "wire-parity",
                f"kHistOpSlots is {slots} but max WireOp opcode is {max_op} — "
                f"per-op histograms need max+1 = {max_op + 1} slots or new "
                "ops alias slot 0",
            )
        nm = re.search(r"kWireOpNames\[[^\]]*\]\s*=\s*\{", tele.code_strings)
        if nm:
            seg = tele.code_strings[nm.end() : tele.code_strings.find("}", nm.end())]
            names = re.findall(r'"([^"]*)"', seg)
            if len(names) != slots:
                chk.report(
                    tele, line_of(tele.code, nm.start()), "wire-parity",
                    f"kWireOpNames has {len(names)} entries for kHistOpSlots "
                    f"= {slots} — scrape surfaces index this table by opcode",
                )

    service = chk.native("eg_service.cc")
    remote = chk.native("eg_remote.cc")
    for name, val, ln in ops:
        if not re.search(r"\bcase\s+%s\s*:" % re.escape(name), service.code):
            chk.report(
                wire, ln, "wire-parity",
                f"opcode `{name}` has no `case {name}:` in Service::Dispatch "
                "(eg_service.cc) — a client sending it gets the unknown-op "
                "error from every up-to-date server",
            )
        if not re.search(r"\bU8\s*\(\s*%s\s*\)" % re.escape(name), remote.code):
            chk.report(
                wire, ln, "wire-parity",
                f"opcode `{name}` has no client-side `U8({name})` encoder in "
                "eg_remote.cc — dispatch-only ops are dead server code "
                "nothing exercises end to end",
            )


# ---------------------------------------------------------------------------
# Pass: ledger — counter/stat tables vs FAULTS.md vs Python surface
# ---------------------------------------------------------------------------


def parse_name_table(sf: SourceFile, table: str) -> tuple[int, list[str]]:
    m = re.search(r"%s\[[^\]]*\]\s*=\s*\{" % re.escape(table), sf.code_strings)
    if not m:
        return -1, []
    depth = 1
    i = m.end()
    while i < len(sf.code_strings) and depth:
        if sf.code_strings[i] == "{":
            depth += 1
        elif sf.code_strings[i] == "}":
            depth -= 1
        i += 1
    seg = sf.code_strings[m.end() : i - 1]
    return line_of(sf.code_strings, m.start()), re.findall(r'"([^"]*)"', seg)


def faults_glossary_counters(chk: Checker) -> tuple[SourceFile, dict[str, int]]:
    """Counter names from FAULTS.md tables whose header names a counter
    column; returns {name: line}."""
    sf = chk.file("FAULTS.md")
    out: dict[str, int] = {}
    in_table = False
    for ln, line in enumerate(sf.text.split("\n"), 1):
        if re.match(r"\s*\|", line):
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if not in_table:
                if cells and re.search(r"(?i)\bcounter\b", cells[0]):
                    in_table = True
                continue
            if set("".join(cells)) <= set("-: "):
                continue  # separator row
            m = re.match(r"`([\w./]+)`", cells[0])
            if m:
                out.setdefault(m.group(1), ln)
        else:
            in_table = False
    return sf, out


def pass_ledger(chk: Checker):
    stats = chk.native("eg_stats.h")
    counters = parse_enum(stats, "CounterId") or []
    ctr_entries = [(n, v, ln) for n, v, ln in counters if n != "kCtrCount"]
    tbl_line, names = parse_name_table(stats, "kCounterNames")
    if tbl_line < 0:
        chk.report(stats, 1, "ledger-parity", "kCounterNames table not found")
        return
    if len(names) != len(ctr_entries):
        chk.report(
            stats, tbl_line, "ledger-parity",
            f"kCounterNames has {len(names)} entries but enum CounterId has "
            f"{len(ctr_entries)} (excluding kCtrCount) — every snapshot "
            "surface indexes names by counter id",
        )
    dup = {n for n in names if names.count(n) > 1}
    if dup:
        chk.report(
            stats, tbl_line, "ledger-parity",
            f"duplicate counter name(s): {', '.join(sorted(dup))} — two ids "
            "collapse into one dashboard series",
        )
    stat_entries = [
        (n, v, ln)
        for n, v, ln in (parse_enum(stats, "StatOp") or [])
        if n != "kStatOpCount"
    ]
    stbl_line, stat_names = parse_name_table(stats, "kStatNames")
    if stbl_line >= 0 and len(stat_names) != len(stat_entries):
        chk.report(
            stats, stbl_line, "ledger-parity",
            f"kStatNames has {len(stat_names)} entries but enum StatOp has "
            f"{len(stat_entries)} (excluding kStatOpCount)",
        )

    faults_sf, documented = faults_glossary_counters(chk)
    for name in names:
        if name not in documented:
            chk.report(
                stats, tbl_line, "ledger-parity",
                f"counter `{name}` is not in any FAULTS.md counter-glossary "
                "table — every ledger entry needs operator-facing semantics",
            )
    for name, ln in sorted(documented.items()):
        if name not in names:
            chk.report(
                faults_sf, ln, "ledger-parity",
                f"FAULTS.md documents counter `{name}` that eg_stats.h does "
                "not define — stale glossary rows misdirect an incident",
            )

    # counters() docstring name-drops must be real counters
    py = chk.file("euler_tpu", "graph", "native.py")
    tree = ast.parse(py.text)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "counters":
            doc = ast.get_docstring(node) or ""
            for tok in re.findall(r'"(\w+)":', doc):
                if tok not in names:
                    chk.report(
                        py, node.lineno, "ledger-parity",
                        f"counters() docstring quotes `\"{tok}\"` which is "
                        "not a counter in eg_stats.h kCounterNames",
                    )


# ---------------------------------------------------------------------------
# Pass: config — parsed keys vs README tables vs graph.py vs run_loop
# ---------------------------------------------------------------------------


def readme_config_tables(chk: Checker):
    """{key: line} from README tables whose header row is |key|default|…."""
    sf = chk.file("README.md")
    out: dict[str, int] = {}
    in_table = False
    for ln, line in enumerate(sf.text.split("\n"), 1):
        if re.match(r"\s*\|", line):
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if not in_table:
                if cells and cells[0].lower() == "key":
                    in_table = True
                continue
            if set("".join(cells)) <= set("-: "):
                continue
            for key in re.findall(r"`(\w+)`", cells[0]):
                out.setdefault(key, ln)
        else:
            in_table = False
    return sf, out


def pass_config(chk: Checker):
    remote = chk.native("eg_remote.cc")
    remote_keys: dict[str, int] = {}
    for m in re.finditer(
        r'cfg\s*(?:\.\s*(?:count|find|at)\s*\(|\[)\s*"(\w+)"', remote.code_strings
    ):
        remote_keys.setdefault(m.group(1), line_of(remote.code_strings, m.start()))

    admission = chk.native("eg_admission.cc")
    admission_keys: dict[str, int] = {}
    for m in re.finditer(r'key\s*==\s*"(\w+)"', admission.code_strings):
        admission_keys.setdefault(
            m.group(1), line_of(admission.code_strings, m.start())
        )

    graph = chk.file("euler_tpu", "graph", "graph.py")
    km = re.search(r"known\s*=\s*\{([^}]*)\}", graph.text)
    graph_known = set(re.findall(r'"(\w+)"', km.group(1))) if km else set()

    run_loop = chk.file("euler_tpu", "run_loop.py")
    flags = set(re.findall(r'add_argument\(\s*"--(\w+)"', run_loop.text))

    readme_sf, readme_keys = readme_config_tables(chk)
    # a key counts as "mentioned" if it appears as a word inside ANY backtick
    # span (`timeout_ms` inside a compound table cell counts) or inside a
    # fenced code block; fences are cut first so ``` does not desync the
    # inline-span regex
    readme_all = set()
    fence_re = re.compile(r"```.*?```", re.S)
    for block in fence_re.findall(readme_sf.text):
        readme_all.update(re.findall(r"\w+", block))
    for span in re.findall(r"`([^`\n]+)`", fence_re.sub("", readme_sf.text)):
        readme_all.update(re.findall(r"\w+", span))

    for key, ln in sorted(remote_keys.items()):
        if key not in graph_known:
            chk.report(
                remote, ln, "config-parity",
                f"eg_remote.cc parses config key `{key}` that graph.py's "
                "`known` kwarg set never forwards — unreachable from the "
                "public Graph surface",
            )
        if key not in readme_all:
            chk.report(
                remote, ln, "config-parity",
                f"eg_remote.cc parses config key `{key}` that README.md "
                "never mentions — operators cannot discover it",
            )
    for key, ln in sorted(admission_keys.items()):
        if key not in readme_all:
            chk.report(
                admission, ln, "config-parity",
                f"service option `{key}` (ParseAdmissionOptions) is not "
                "documented anywhere in README.md — undiscoverable knob",
            )
    parsed_somewhere = (
        set(remote_keys) | set(admission_keys) | graph_known | flags
    )
    for key, ln in sorted(readme_keys.items()):
        if key not in parsed_somewhere:
            chk.report(
                readme_sf, ln, "config-parity",
                f"README config table documents key `{key}` that nothing "
                "parses (eg_remote.cc / eg_admission.cc / graph.py known / "
                "run_loop flags) — a documented no-op",
            )


# ---------------------------------------------------------------------------
# Pass: lock — EG_GUARDED_BY fields only touched under their guard
# ---------------------------------------------------------------------------

ANNOT_RE = re.compile(
    r"\b(\w+)\s*((?:\[[^\][]*\]\s*)*)\s*EG_GUARDED_BY\s*\(\s*(\w+)\s*\)"
)
REQUIRES_RE = re.compile(r"EG_REQUIRES\s*\(\s*(\w+)\s*\)")
GUARD_RE = re.compile(
    r"\b(?:lock_guard|unique_lock|scoped_lock)\s*"
    r"(?:<[^<>;]*>)?\s+\w+\s*[({]([^;{}]*?)[)}]"
)


def _requires_blocks(sf: SourceFile):
    """[(mutex, block, fn_name)] for every EG_REQUIRES-marked function
    DEFINITION (a `;` before the `{` means declaration — no body here)."""
    out = []
    for m in REQUIRES_RE.finditer(sf.code):
        j = m.end()
        while j < len(sf.code) and sf.code[j] not in ";{":
            j += 1
        if j >= len(sf.code) or sf.code[j] == ";":
            continue
        for b in sf.blocks:
            if b.start == j and b.kind == "function":
                out.append((m.group(1), b, b.name.split("::")[-1]))
                break
    return out


def _requires_names(sf: SourceFile) -> dict[str, str]:
    """{function name: mutex} for every EG_REQUIRES-marked declaration or
    definition in the file (call sites of these must hold the mutex)."""
    out = {}
    for m in REQUIRES_RE.finditer(sf.code):
        head = sf.code[: m.start()]
        nm = re.search(r"([~\w:]+)\s*\([^()]*\)\s*(?:const\s*)?$", head)
        if nm:
            out[nm.group(1).split("::")[-1]] = m.group(1)
    return out


def _guard_covers(region: str, mutex: str) -> bool:
    """True when some RAII guard on `mutex` declared in `region` (the code
    from the enclosing function's opening brace to the use site) is still
    in scope at the end of the region (brace-aware)."""
    mu_re = re.compile(r"(?:^|[^\w])%s\b" % re.escape(mutex))
    for g in GUARD_RE.finditer(region):
        if not mu_re.search(g.group(1)):
            continue
        depth = 0
        ok = True
        for ch in region[g.end():]:
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth < 0:  # the guard's scope closed before the use
                    ok = False
                    break
        if ok:
            return True
    return False


def _is_ctor_dtor(chain, use_off, code) -> bool:
    """Innermost enclosing *function* is a constructor/destructor."""
    for b in reversed(chain):
        if b.kind == "lambda":
            continue
        if b.kind != "function":
            return False
        name = b.name
        if "~" in name:
            return True
        parts = [p for p in name.split("::") if p]
        if len(parts) >= 2 and parts[-1] == parts[-2]:
            return True
        # header-inline ctor: function name equals an enclosing type name
        for t in chain:
            if t.kind == "type" and t.name and t.name == name:
                return True
        return False
    return False


def pass_lock(chk: Checker):
    native_dir = os.path.join(chk.root, "euler_tpu", "graph", "_native")
    files = sorted(
        f for f in os.listdir(native_dir) if f.endswith((".h", ".cc"))
    )
    # collect annotations per file stem
    annots: dict[str, list[tuple[str, str, int]]] = {}
    any_annot = False
    for fname in files:
        sf = chk.native(fname)
        for m in ANNOT_RE.finditer(sf.code):
            ln = line_of(sf.code, m.start())
            line_text = sf.lines[ln - 1].lstrip()
            if line_text.startswith("#"):
                continue  # the macro definition itself
            stem = fname.rsplit(".", 1)[0]
            annots.setdefault(stem, []).append((m.group(1), m.group(3), ln))
            any_annot = True
    if not any_annot:
        common = chk.native("eg_common.h")
        chk.report(
            common, 1, "guarded-by",
            "no EG_GUARDED_BY annotations found anywhere — the lock pass "
            "has nothing to check (macro deleted or annotations stripped?)",
        )
        return
    for stem, fields in sorted(annots.items()):
        decl_lines = {(f, ln) for f, _, ln in fields}
        req_names: dict[str, str] = {}
        req_blocks = []
        for ext in (".h", ".cc"):
            fname = stem + ext
            if fname not in files:
                continue
            sf = chk.native(fname)
            req_names.update(_requires_names(sf))
            req_blocks.append((sf, _requires_blocks(sf)))
        for ext in (".h", ".cc"):
            fname = stem + ext
            if fname not in files:
                continue
            sf = chk.native(fname)
            sf_req = dict(req_blocks).get(sf, [])
            for field, mutex in sorted(set((f, m) for f, m, _ in fields)):
                for um in re.finditer(r"\b%s\b" % re.escape(field), sf.code):
                    off = um.start()
                    ln = line_of(sf.code, off)
                    if (field, ln) in decl_lines and sf.path.endswith(
                        stem + ".h"
                    ):
                        continue  # the annotated declaration itself
                    tail = sf.code[um.end():um.end() + 2].lstrip()
                    if tail.startswith("("):
                        continue  # a method CALL named like the field
                    chain = [
                        b
                        for b in sf.blocks
                        if b.start < off <= (b.end if b.end >= 0 else len(sf.code))
                    ]
                    if not any(
                        b.kind in ("function", "lambda") for b in chain
                    ):
                        continue  # declarations, sizeof, member-init lists
                    if _is_ctor_dtor(chain, off, sf.code):
                        continue
                    if any(
                        mu == mutex and b.start < off <= b.end
                        for mu, b, _ in sf_req
                    ):
                        continue  # inside an EG_REQUIRES(mu) helper body
                    outer = next(
                        b for b in chain if b.kind in ("function", "lambda")
                    )
                    region = sf.code[outer.start + 1 : off]
                    if _guard_covers(region, mutex):
                        continue
                    chk.report(
                        sf, ln, "guarded-by",
                        f"`{field}` is EG_GUARDED_BY({mutex}) but this scope "
                        f"holds no RAII guard on {mutex} — lock it or add a "
                        "reasoned allow(guarded-by) escape for a documented "
                        "lock-free access",
                    )
        # call sites of EG_REQUIRES-marked helpers must themselves hold the
        # mutex (or sit inside another EG_REQUIRES body for the same mutex)
        for ext in (".h", ".cc"):
            fname = stem + ext
            if fname not in files:
                continue
            sf = chk.native(fname)
            sf_req = dict(req_blocks).get(sf, [])
            for fn_name, mutex in sorted(req_names.items()):
                for cm in re.finditer(
                    r"\b%s\s*\(" % re.escape(fn_name), sf.code
                ):
                    off = cm.start()
                    ln = line_of(sf.code, off)
                    chain = [
                        b
                        for b in sf.blocks
                        if b.start < off <= (b.end if b.end >= 0 else len(sf.code))
                    ]
                    fn_chain = [
                        b for b in chain if b.kind in ("function", "lambda")
                    ]
                    if not fn_chain:
                        continue  # the declaration/definition header itself
                    inner = fn_chain[-1]
                    if inner.kind == "function" and (
                        inner.name.split("::")[-1] == fn_name
                    ):
                        continue  # recursion within the helper itself
                    if any(
                        mu == mutex and b.start < off <= b.end
                        for mu, b, _ in sf_req
                    ):
                        continue  # caller is itself EG_REQUIRES(mu)
                    outer = fn_chain[0]
                    region = sf.code[outer.start + 1 : off]
                    if _guard_covers(region, mutex):
                        continue
                    chk.report(
                        sf, ln, "guarded-by",
                        f"call to `{fn_name}` which is EG_REQUIRES({mutex}) "
                        f"but this scope holds no RAII guard on {mutex}",
                    )


# ---------------------------------------------------------------------------
# Pass: artifacts — build-artifact hygiene
# ---------------------------------------------------------------------------

ARTIFACT_RE = re.compile(r"\.(?:o|so|a)$|(?:^|/)\.flavor$|(?:^|/)\.sanitize/")


def pass_artifacts(chk: Checker):
    try:
        ls = subprocess.run(
            ["git", "ls-files"],
            cwd=chk.root,
            capture_output=True,
            text=True,
            check=True,
        ).stdout.splitlines()
    except (OSError, subprocess.CalledProcessError):
        print(
            "NOTE: artifacts pass skipped tracked-file check (git unavailable)",
            file=sys.stderr,
        )
        ls = []
    for path in ls:
        if ARTIFACT_RE.search(path):
            chk.violations.append(
                Violation(
                    path, 1, "artifact-hygiene",
                    "build artifact is tracked in git — binaries/flavor "
                    "markers are machine-local state (make products); "
                    "`git rm --cached` it",
                )
            )
    native_dir = os.path.join(chk.root, "euler_tpu", "graph", "_native")
    for fname in sorted(os.listdir(native_dir)):
        if fname.endswith(".o") and not os.path.exists(
            os.path.join(native_dir, fname[:-2] + ".cc")
        ):
            chk.violations.append(
                Violation(
                    chk.rel(os.path.join(native_dir, fname)), 1,
                    "artifact-hygiene",
                    f"orphan object: {fname} has no matching .cc — a stale "
                    "object from a deleted source can shadow real symbols "
                    "at link time (the stale-object incident ROADMAP "
                    "recorded); delete it",
                )
            )
    gi_path = os.path.join(chk.root, ".gitignore")
    patterns = set()
    if os.path.exists(gi_path):
        with open(gi_path) as f:
            patterns = {line.strip() for line in f if line.strip()}
    gi_sf = None
    for needed in ("*.o", "*.so", ".flavor", ".sanitize/"):
        if needed not in patterns:
            if gi_sf is None:
                gi_sf = SourceFile(gi_path) if os.path.exists(gi_path) else None
            chk.violations.append(
                Violation(
                    ".gitignore", 1, "artifact-hygiene",
                    f"missing `{needed}` pattern — freshly built artifacts "
                    "would show up as untracked noise and invite commits",
                )
            )


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


PASS_FUNCS = {
    "abi": pass_abi,
    "wire": pass_wire,
    "ledger": pass_ledger,
    "config": pass_config,
    "lock": pass_lock,
    "artifacts": pass_artifacts,
}


def run_passes(root: str, passes=None) -> list[Violation]:
    chk = Checker(root)
    active = list(passes) if passes else list(PASSES)
    for name in active:
        PASS_FUNCS[name](chk)
    chk.audit_stale_escapes({RULE_OF_PASS[n] for n in active})
    chk.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return chk.violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument(
        "--passes", help="comma-separated subset of passes (see --list-passes)"
    )
    ap.add_argument("--list-passes", action="store_true")
    ap.add_argument(
        "--root",
        default=os.path.dirname(_HERE),
        help="repo root (default: the parent of this script's directory)",
    )
    args = ap.parse_args(argv)

    if args.list_passes:
        for name, desc in PASSES.items():
            print(f"{name:10s} [{RULE_OF_PASS[name]}] {desc}")
        return 0

    passes = None
    if args.passes:
        passes = [p.strip() for p in args.passes.split(",") if p.strip()]
        unknown = [p for p in passes if p not in PASSES]
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
            return 2

    try:
        violations = run_passes(args.root, passes)
    except FileNotFoundError as e:
        print(f"cannot read {e.filename}", file=sys.stderr)
        return 2

    for v in violations:
        print(v)
    names = passes or list(PASSES)
    if violations:
        print(f"\n{len(violations)} violation(s) across {len(names)} pass(es)")
        return 1
    print(f"clean: {len(names)} pass(es) ({', '.join(names)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
