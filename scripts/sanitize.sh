#!/usr/bin/env bash
# One-command sanitizer round (SANITIZERS.md): flavor-aware side build,
# suite run under the preloaded runtime, report parsing to a hard
# pass/fail, and a machine-readable round record under evidence/.
#
# The instrumented library is built OUT OF TREE, into
# _native/.sanitize/<flavor>/ (sources copied with their mtimes, so the
# side build is incremental across rounds), and the test process loads
# it via EG_NATIVE_LIB — the in-tree libeuler_graph.so and its .flavor
# state machine are never touched, so a sanitizer round composes with a
# normal dev loop instead of forcing two full rebuilds around itself.
#
# Usage:
#   scripts/sanitize.sh                     # tsan over the default suites
#   scripts/sanitize.sh --flavor asan       # asan instead
#   scripts/sanitize.sh --smoke             # small tsan slice (verify.sh gate)
#   scripts/sanitize.sh --suites "tests/test_remote.py -k dedup"
#
# Verdict: PASS only when pytest exits 0 AND no FIRST-PARTY sanitizer
# report fired. Per tsan.supp policy, a report is first-party only if an
# eg_* / libeuler_graph frame appears in it; runtime noise from the
# bundled jaxlib/BLAS stacks is suppressed or ignored. Every round
# appends one JSON line to evidence/sanitizer_rounds/rounds.jsonl.
set -uo pipefail
cd "$(dirname "$0")/.."

FLAVOR=tsan
SUITES=""
SMOKE=0
while [ $# -gt 0 ]; do
  case "$1" in
    --flavor) FLAVOR="$2"; shift 2 ;;
    --suites) SUITES="$2"; shift 2 ;;
    --smoke)  SMOKE=1; shift ;;
    *) echo "sanitize.sh: unknown arg $1" >&2; exit 2 ;;
  esac
done
case "$FLAVOR" in tsan|asan) ;; *)
  echo "sanitize.sh: --flavor must be tsan or asan" >&2; exit 2 ;;
esac
if [ -z "$SUITES" ]; then
  if [ "$SMOKE" -eq 1 ]; then
    # The smoke slice: the malformed-frame fuzz barrage — 16 threads of
    # garbage + concurrent valid traffic against a live service — is the
    # densest concurrency per second of wall clock in the tree (<1 s
    # uninstrumented), so it is the slice verify.sh can afford.
    SUITES="tests/test_wire_fuzz.py"
  else
    # The round-8 set (SANITIZERS.md): seeded faults interleaving with
    # the worker pool, the fuzz barrage, and registry churn.
    SUITES="tests/test_fault_injection.py tests/test_wire_fuzz.py tests/test_registry.py"
  fi
fi

NATIVE=euler_tpu/graph/_native
SIDE="$NATIVE/.sanitize/$FLAVOR"
RUNTIME=$(g++ -print-file-name=lib${FLAVOR}.so)
if [ ! -f "$RUNTIME" ]; then
  echo "sanitize.sh: lib${FLAVOR}.so not found in the toolchain" >&2
  exit 2
fi

echo "== sanitize: $FLAVOR side build ($SIDE) =="
mkdir -p "$SIDE"
# -p keeps mtimes so make only recompiles what actually changed; -u
# skips files the side copy already has current.
cp -pu "$NATIVE"/*.cc "$NATIVE"/*.h "$NATIVE"/Makefile "$NATIVE"/tsan.supp "$SIDE"/
build_t0=$(date +%s)
if [ "$FLAVOR" = tsan ]; then SFLAG=thread; else SFLAG=address; fi
make -C "$SIDE" -s FLAVOR="$FLAVOR" \
  CXXFLAGS="-O1 -g -fPIC -std=c++17 -Wall -Wextra -fopenmp -pthread -fsanitize=$SFLAG" \
  LDFLAGS="-shared -fopenmp -pthread -fsanitize=$SFLAG" || {
    echo "sanitize.sh: instrumented build failed" >&2; exit 1; }
build_t1=$(date +%s)

LOGDIR=$(mktemp -d /tmp/sanitize.XXXXXX)
export EG_NATIVE_LIB="$PWD/$SIDE/libeuler_graph.so"
export JAX_PLATFORMS=cpu
# exitcode=0: the sanitizer must not hijack pytest's exit status — the
# verdict below reads the parsed reports, not the process rc.
if [ "$FLAVOR" = tsan ]; then
  export TSAN_OPTIONS="suppressions=$PWD/$NATIVE/tsan.supp exitcode=0 log_path=$LOGDIR/report"
else
  # detect_leaks=0: CPython's arena allocations drown the leak report
  export ASAN_OPTIONS="detect_leaks=0 exitcode=0 halt_on_error=0 log_path=$LOGDIR/report"
fi

echo "== sanitize: $FLAVOR run: pytest $SUITES =="
run_t0=$(date +%s)
# eval-split so a quoted -k expression inside --suites survives intact.
# Deliberately NOT `bash -c` under the preload: bash itself loaded with
# libtsan segfaults on longer command lines in this image (reproduced
# with --collect-only; python under the same preload is fine), so only
# timeout→python run instrumented.
eval "set -- $SUITES"
LD_PRELOAD="$RUNTIME" timeout -k 10 900 \
  python -m pytest "$@" -q -p no:cacheprovider
pytest_rc=$?
run_t1=$(date +%s)

python - "$LOGDIR" "$FLAVOR" "$SUITES" "$pytest_rc" \
  $((build_t1 - build_t0)) $((run_t1 - run_t0)) $SMOKE <<'EOF'
import glob, json, os, re, sys, time

logdir, flavor, suites, pytest_rc, build_s, run_s, smoke = (
    sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4]),
    int(sys.argv[5]), int(sys.argv[6]), sys.argv[7] == "1")
head_re = re.compile(
    r"WARNING: ThreadSanitizer|ERROR: (?:Address|Thread)Sanitizer")
first_party_re = re.compile(r"\beg_\w+|libeuler_graph")
total = first_party = 0
samples = []
for path in sorted(glob.glob(os.path.join(logdir, "report*"))):
    with open(path, errors="replace") as f:
        text = f.read()
    # reports are separated by their SUMMARY trailer; split per report
    # so the first-party test inspects one stack set at a time
    blocks, cur = [], []
    for line in text.splitlines():
        cur.append(line)
        if line.startswith("SUMMARY:"):
            blocks.append("\n".join(cur))
            cur = []
    if cur:
        blocks.append("\n".join(cur))
    for b in blocks:
        if not head_re.search(b):
            continue
        total += 1
        if first_party_re.search(b):
            first_party += 1
            if len(samples) < 3:
                samples.append(b[:2000])
verdict = "PASS" if pytest_rc == 0 and first_party == 0 else "FAIL"
rec = {
    "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    "flavor": flavor,
    "smoke": smoke,
    "suites": suites,
    "pytest_rc": pytest_rc,
    "reports_total": total,
    "reports_first_party": first_party,
    "build_s": build_s,
    "run_s": run_s,
    "verdict": verdict,
}
os.makedirs("evidence/sanitizer_rounds", exist_ok=True)
with open("evidence/sanitizer_rounds/rounds.jsonl", "a") as f:
    f.write(json.dumps(rec) + "\n")
print(f"== sanitize: {flavor} verdict: {verdict} "
      f"(pytest rc={pytest_rc}, reports={total}, "
      f"first-party={first_party}) ==")
for s in samples:
    print("---- first-party report (truncated) ----")
    print(s)
sys.exit(0 if verdict == "PASS" else 1)
EOF
verdict_rc=$?
rm -rf "$LOGDIR"
exit "$verdict_rc"
