"""Host-path OpenMP core-scaling measurement for the native batch ops.

The host sampling path claims to parallelize with host cores (the batch
ops — sample_fanout, sample_neighbor, dense-feature gathers — run
OpenMP parallel-for over rows, eg_engine.cc). This script measures that
claim directly: per OMP_NUM_THREADS setting it re-execs itself in a
subprocess (OpenMP sizes its thread pool from the env at library load),
builds a synthetic graph at roughly bench dims, and times the batch ops.

    python scripts/omp_scaling.py              # threads 1,2,4,8 (capped
                                               # at the visible cores x2)
    python scripts/omp_scaling.py --threads 1,4,16

Prints one JSON line per setting plus a final summary table suitable
for PERF.md. On a single-core host the extra-thread rows show
contention, not scaling — run on a multi-core box for the real curve.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def measure(num_nodes: int, batch: int, iters: int) -> dict:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import euler_tpu
    from euler_tpu.datasets import build_synthetic

    cache = os.environ.get(
        "EULER_TPU_BENCH_CACHE", "/tmp/euler_tpu_omp_scaling"
    )
    build_synthetic(
        cache, num_nodes=num_nodes, avg_degree=15, feature_dim=50,
        label_dim=8, multilabel=False,
    )
    g = euler_tpu.Graph(directory=cache)
    roots = g.sample_node(batch, -1)
    fanouts = [10, 10]
    edge_types = [[0]] * len(fanouts)

    def timed(fn):
        fn()  # warm (page in, thread pool spin-up)
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters * 1e3

    fanout_ms = timed(lambda: g.sample_fanout(roots, edge_types, fanouts))
    ids2 = g.sample_fanout(roots, edge_types, fanouts)[0][-1]
    nbr_ms = timed(lambda: g.sample_neighbor(ids2, [0], 10))
    feat_ms = timed(lambda: g.get_dense_feature(ids2, [1], [50]))
    edges = batch * (fanouts[0] + fanouts[0] * fanouts[1])
    return {
        "omp_num_threads": int(os.environ.get("OMP_NUM_THREADS", 0)),
        "sample_fanout_ms": round(fanout_ms, 3),
        "fanout_edges_per_sec": round(edges / (fanout_ms / 1e3), 1),
        "sample_neighbor_ms": round(nbr_ms, 3),
        "dense_feature_ms": round(feat_ms, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--threads", default=None,
                    help="comma list; default 1,2,4,8 capped at 2x cores")
    ap.add_argument("--num-nodes", type=int, default=56944)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        print(json.dumps(measure(args.num_nodes, args.batch, args.iters)),
              flush=True)
        return

    cores = len(os.sched_getaffinity(0))
    if args.threads:
        threads = [int(t) for t in args.threads.split(",")]
    else:
        threads = [t for t in (1, 2, 4, 8) if t <= 2 * cores] or [1]
    rows = []
    for t in threads:
        env = dict(os.environ, OMP_NUM_THREADS=str(t))
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--num-nodes", str(args.num_nodes), "--batch",
             str(args.batch), "--iters", str(args.iters)],
            env=env, capture_output=True, text=True,
        )
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        if r.returncode != 0 or not line:
            print(json.dumps({"omp_num_threads": t,
                              "error": r.stderr.strip()[-200:]}))
            continue
        row = json.loads(line)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if rows:
        # baseline = the fewest-threads row that succeeded (rows[0] would
        # invert the curve under --threads 8,4,1 or a failed t=1 child)
        base = min(rows, key=lambda r: r["omp_num_threads"])[
            "sample_fanout_ms"
        ]
        print(f"\nvisible cores: {cores}")
        print("threads  fanout_ms  speedup  nbr_ms  feat_ms")
        for r in rows:
            print(
                f"{r['omp_num_threads']:>7}  {r['sample_fanout_ms']:>9}"
                f"  {base / r['sample_fanout_ms']:>7.2f}"
                f"  {r['sample_neighbor_ms']:>6}"
                f"  {r['dense_feature_ms']:>7}"
            )


if __name__ == "__main__":
    main()
