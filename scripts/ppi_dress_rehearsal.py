"""Full-scale dress rehearsal of the real-PPI data path.

Zero egress means the real GraphSAGE PPI release cannot be downloaded
here, so the prep pipeline (prepare_ppi -> .dat partitions -> ppi_main
training -> id-file evaluation) had only ever run on miniature unit
fixtures. This script builds a FULL-SIZE synthetic replica of the
release layout — 56944 nodes, 50-dim feats.npy, 121-dim multilabel
class_map, ~818k node-link edges, the real split PROPORTIONS (~79%
train / ~11% val / ~10% test, drawn per node so exact counts vary),
identity id_map, a few unannotated nodes to exercise the drop path —
and drives it end-to-end exactly the way a user with the real files
would:

    python scripts/ppi_dress_rehearsal.py [--num-nodes N] [--epochs E]

Labels are a fixed random linear function of the features, so training
F1 moving above chance also proves the model is learning from the
prepared files, not just executing. The recorded full-size run lives in
README.md; tests/test_prepare_real.py runs a shrunken version as a
slow-marked test.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_replica(prefix: str, num_nodes: int, num_links: int,
                  feature_dim: int = 50, label_dim: int = 121,
                  val_frac: float = 0.114, test_frac: float = 0.097,
                  seed: int = 0) -> dict:
    """GraphSAGE-release-format files at ``prefix``; returns split sizes."""
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((num_nodes, feature_dim)).astype(np.float32)
    np.save(prefix + "-feats.npy", feats)

    # labels: thresholded random projection of the features -> learnable
    w = rng.standard_normal((feature_dim, label_dim)) / np.sqrt(feature_dim)
    logits = feats @ w
    labels = (logits > 0).astype(float)

    u = rng.random(num_nodes)
    is_val = u < val_frac
    is_test = (u >= val_frac) & (u < val_frac + test_frac)
    nodes = []
    drop = set(
        rng.choice(num_nodes, size=max(2, num_nodes // 20000), replace=False)
        .tolist()
    )
    for i in range(num_nodes):
        if i in drop:  # unannotated rows: prepare_ppi must drop them
            nodes.append({"id": i})
        else:
            nodes.append(
                {"id": i, "val": bool(is_val[i]), "test": bool(is_test[i])}
            )
    src = rng.integers(0, num_nodes, num_links)
    dst = rng.integers(0, num_nodes, num_links)
    links = [
        {"source": int(s), "target": int(t)}
        for s, t in zip(src, dst) if s != t
    ]
    with open(prefix + "-G.json", "w") as f:
        json.dump({"nodes": nodes, "links": links}, f)
    with open(prefix + "-id_map.json", "w") as f:
        json.dump({str(i): i for i in range(num_nodes)}, f)
    with open(prefix + "-class_map.json", "w") as f:
        json.dump({str(i): labels[i].tolist() for i in range(num_nodes)}, f)
    kept = ~np.isin(np.arange(num_nodes), list(drop))
    # trivial-predictor micro-F1 on the val labels — predicting every
    # label positive scores 2p/(1+p); a model that learned the linear
    # label function must clear it by a margin (the test gate)
    p = float(labels[kept & is_val].mean())
    return {
        "train": int((kept & ~is_val & ~is_test).sum()),
        "val": int((kept & is_val).sum()),
        "test": int((kept & is_test).sum()),
        "links": len(links),
        "allpos_f1": round(2 * p / (1 + p), 4),
    }


def run(num_nodes: int, num_links: int, epochs: int, batch_size: int,
        dim: int, workdir: str | None = None) -> dict:
    from euler_tpu import ppi_main
    from euler_tpu.datasets import prepare_ppi

    own_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="ppi_rehearsal_")
    prefix = os.path.join(workdir, "ppi")
    out = os.path.join(workdir, "dat")
    model_dir = os.path.join(workdir, "ck")
    summary: dict = {"num_nodes": num_nodes}
    try:
        t0 = time.time()
        summary["splits"] = write_replica(prefix, num_nodes, num_links)
        summary["write_replica_s"] = round(time.time() - t0, 1)

        t1 = time.time()
        prepare_ppi(prefix, out, num_partitions=2)
        summary["prepare_ppi_s"] = round(time.time() - t1, 1)

        common = [
            "--data_dir", out, "--model_dir", model_dir,
            "--model", "graphsage_supervised",
            "--max_id", str(num_nodes - 1),
            "--batch_size", str(batch_size), "--dim", str(dim),
            "--fanouts", "10,10", "--train_edge_type", "0",
            "--num_epochs", str(epochs), "--log_steps", "20",
        ]
        t2 = time.time()
        rc = ppi_main.run(common + ["--mode", "train"])
        summary["train_s"] = round(time.time() - t2, 1)
        summary["train_rc"] = rc
        if rc == 0:
            t3 = time.time()
            rc = ppi_main.run(
                common + [
                    "--mode", "evaluate",
                    "--id_file", os.path.join(out, "val.id"),
                ]
            )
            summary["evaluate_s"] = round(time.time() - t3, 1)
            summary["evaluate_rc"] = rc
            eval_json = os.path.join(model_dir, "eval.json")
            if rc == 0 and os.path.exists(eval_json):
                with open(eval_json) as f:
                    summary["val_metrics"] = json.load(f)
        return summary
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-nodes", type=int, default=56944)
    ap.add_argument("--num-links", type=int, default=818716)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--workdir", default=None,
                    help="keep artifacts here instead of a temp dir")
    args = ap.parse_args()
    summary = run(args.num_nodes, args.num_links, args.epochs,
                  args.batch_size, args.dim, args.workdir)
    print(json.dumps(summary))
    ok = summary.get("train_rc") == 0 and summary.get("evaluate_rc") == 0
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
