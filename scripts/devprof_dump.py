#!/usr/bin/env python3
"""Device-plane report: compile economics, recompile ledger, memory
high-water, and the top-K kernels of a jax.profiler capture.

Three input modes, combinable (OBSERVABILITY.md "Device plane"):

  * ``--profile_dir DIR`` — read a ``jax.profiler`` capture (the
    training run's ``--profile_dir``) and print the top-K device
    kernels by total self time, plus the lane inventory;
  * ``--registry`` / ``--shards`` — scrape a live cluster and print
    each shard's compile table (compiles, recompiles, compile wall,
    transfer volume, device-memory high-water) and serve SLO gauges;
  * ``--smoke`` — self-contained drill (verify.sh gate): jit a step,
    inject a shape drift, assert exactly one journaled recompile with
    the offending diff, capture a profiler trace around it, and
    validate the merged host+device Perfetto export.

Usage:
    python scripts/devprof_dump.py --profile_dir /tmp/prof
    python scripts/devprof_dump.py --registry /shared/reg
    python scripts/devprof_dump.py --smoke
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def kernel_table(profile_dir: str, topk: int = 15) -> list:
    """Top-K device kernels by total self time from a capture; returns
    the aggregated (name, total_us, count) rows it printed."""
    from euler_tpu.trace import ingest_profiler_dir

    events = ingest_profiler_dir(profile_dir)
    slices = [e for e in events if e.get("ph") == "X"]
    if not slices:
        print(f"no device slices found under {profile_dir}")
        return []
    lanes = {e["pid"] for e in slices}
    agg: dict = defaultdict(lambda: [0, 0])
    for e in slices:
        agg[e["name"]][0] += e["dur"]
        agg[e["name"]][1] += 1
    rows = sorted(
        ((name, tot, cnt) for name, (tot, cnt) in agg.items()),
        key=lambda r: r[1], reverse=True,
    )[:topk]
    print(f"== device kernels ({len(slices)} slices, "
          f"{len(lanes)} lane(s)) ==")
    print(f"  {'kernel':40s} {'total_us':>10s} {'count':>7s} "
          f"{'avg_us':>9s}")
    for name, tot, cnt in rows:
        print(f"  {name[:40]:40s} {tot:10d} {cnt:7d} {tot / cnt:9.1f}")
    return rows


def compile_table(sources: list) -> None:
    """Per-source compile economics rows from telemetry dumps:
    [(label, dump_dict), ...] (a scrape, or this process's)."""
    from euler_tpu import devprof

    print("== compile table ==")
    print(f"  {'source':10s} {'compiles':>8s} {'recomp':>6s} "
          f"{'serve_rc':>8s} {'compile_ms':>10s} {'p99_ms':>8s} "
          f"{'h2d_MB':>8s} {'d2h_MB':>8s} {'mem_peak_MB':>11s}")
    for label, data in sources:
        s = devprof.compile_summary(data)
        print(f"  {label:10s} {s['compiles']:8d} {s['recompiles']:6d} "
              f"{s['serve_recompiles']:8d} {s['compile_ms_total']:10.1f} "
              f"{s['compile_ms_p99']:8.1f} "
              f"{s['h2d_bytes'] / 1e6:8.1f} {s['d2h_bytes'] / 1e6:8.1f} "
              f"{s['device_mem_peak_bytes'] / 1e6:11.1f}")
        slo = data.get("serve_slo")
        if slo and slo.get("count"):
            print(f"  {label:10s} serve SLO: p50 "
                  f"{slo['p50_us'] / 1000.0:.1f}ms p99 "
                  f"{slo['p99_us'] / 1000.0:.1f}ms "
                  f"violations {slo['violations']}/{slo['count']}")


def ledger_table() -> None:
    """This process's journaled post-warmup recompiles."""
    from euler_tpu import devprof

    led = devprof.recompile_ledger()
    if not led:
        return
    print(f"== recompile ledger ({len(led)}) ==")
    for e in led:
        print(f"  {e['fn']}: {'; '.join(e['diff'])} "
              f"(wall {e['wall_us'] / 1000.0:.1f}ms)")


def run_smoke() -> int:
    """Self-contained device-plane drill (verify.sh gate)."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from euler_tpu import devprof
    from euler_tpu import telemetry as T
    from euler_tpu import trace as trace_mod

    T.telemetry_reset()
    devprof.devprof_reset()
    devprof.install()
    step = devprof.watch(
        jax.jit(lambda x: (x @ x.T).sum()), name="smoke_step"
    )
    x = jnp.ones((64, 32), jnp.float32)
    step(x).block_until_ready()  # warmup: first compile, not a recompile

    prof = tempfile.mkdtemp(prefix="euler_devprof_smoke_")
    t0 = trace_mod.now_us()
    jax.profiler.start_trace(prof)
    with trace_mod.align_annotation():
        pass
    step(x).block_until_ready()  # in-bucket: no compile
    # injected shape drift: the classic silent 100x, detected loudly
    step(jnp.ones((48, 32), jnp.float32)).block_until_ready()
    jax.profiler.stop_trace()
    t1 = trace_mod.now_us()

    devprof.sample_device_mem()
    s = devprof.compile_summary()
    assert s["recompiles"] == 1, s
    assert s["compiles"] >= 2, s  # warmup + drift at minimum
    led = devprof.recompile_ledger()
    assert len(led) == 1 and led[0]["fn"] == "smoke_step", led
    assert any("->" in d for d in led[0]["diff"]), led
    assert s["device_mem_bytes"] > 0 and s["device_buffers"] > 0, s

    # merged export: device lanes present, time-aligned, valid
    events = trace_mod.ingest_profiler_dir(prof)
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs, "no device slices ingested"
    assert all(e["pid"] >= trace_mod.PID_DEVICE_BASE for e in xs), xs[:3]
    pad = 2_000_000  # capture bracketing slack, µs
    aligned = [e for e in xs if t0 - pad <= e["ts"] <= t1 + pad]
    assert len(aligned) == len(xs), (len(aligned), len(xs))
    trace = trace_mod.chrome_trace(base_events=events)
    trace_mod.validate_chrome_trace(trace)

    compile_table([("local", T.telemetry_json())])
    ledger_table()
    kernel_table(prof, topk=5)
    print("devprof_dump smoke: OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--profile_dir", default="", help=(
        "jax.profiler capture directory (the run's --profile_dir) for "
        "the top-K kernel table"))
    ap.add_argument("--topk", type=int, default=15,
                    help="kernel rows to print")
    ap.add_argument("--registry", default="", help=(
        "registry dir or tcp://host:port — scrape the live cluster's "
        "compile tables"))
    ap.add_argument("--shards", default="",
                    help="explicit comma-separated host:port shard list")
    ap.add_argument("--timeout_ms", type=int, default=3000)
    ap.add_argument("--smoke", action="store_true", help=(
        "self-contained recompile + merged-trace drill (verify.sh)"))
    args = ap.parse_args()

    if args.smoke:
        return run_smoke()
    if not args.profile_dir and not args.registry and not args.shards:
        ap.error("need --profile_dir, --registry/--shards, or --smoke")

    if args.registry or args.shards:
        import euler_tpu
        from euler_tpu import telemetry as T

        g = euler_tpu.Graph(
            mode="remote",
            registry=args.registry or None,
            shards=args.shards.split(",") if args.shards else None,
            retries=2,
            timeout_ms=args.timeout_ms,
            rediscover_ms=0,
        )
        try:
            compile_table([
                (f"shard {s}", T.scrape(g, s))
                for s in range(g.num_shards)
            ])
        finally:
            g.close()
    if args.profile_dir:
        kernel_table(args.profile_dir, topk=args.topk)
    return 0


if __name__ == "__main__":
    sys.exit(main())
