#!/usr/bin/env python3
"""Structural invariant linter for the native graph engine.

Machine-checks the crash-class rules the sanitizer/fuzz rounds taught us
(SANITIZERS.md), so a future PR cannot silently reintroduce them. No
libclang: this is a line/brace-aware scan of euler_tpu/graph/_native —
deliberately structural, because every rule below names a *shape* of code
(a missing catch, a raw pointer-overflow compare, an unbounded resize)
that survives renaming and refactoring.

Rules (each individually testable, see tests/test_static_analysis.py):

  abi-barrier      every function defined inside an `extern "C"` block
                   wraps its body in a try/catch barrier (or EG_API_GUARD).
                   An exception crossing the C ABI is std::terminate ->
                   SIGABRT for the host Python process.
  ptr-arith-bounds no `p + n * sizeof(T) > end` style bounds compares:
                   the addition overflows for corrupt huge n and slips
                   past the bound (the round-2 loader crash class). Use
                   division against remaining(), like eg::ByteCursor.
  thread-catch     every thread entry lambda (std::thread ctor or
                   emplace_back on a vector<std::thread>) has a top-level
                   catch: an exception escaping a thread entry is
                   std::terminate for the whole process.
  wire-count-alloc no resize/reserve/new[]/sized-container-construction on
                   a wire- or file-derived count without a preceding bound
                   check (the round-2 service fix: a well-framed request
                   demanding a terabyte result must be rejected before
                   allocation).
  raw-lock         no raw .lock()/.unlock() calls — RAII guards only
                   (lock_guard/unique_lock/scoped_lock), so no early
                   return or exception can leak a held mutex.
  thread-rng       no rand()/srand(): they are process-global and not
                   thread-safe under the OpenMP/pthread samplers — use
                   eg::ThreadRng().

Escapes: a rule can be waived per line with

    // eg-lint: allow(<rule>) <reason>

on the offending line or the line directly above (for function-level
rules: the function header line, the line above it, or the first body
line). The reason is mandatory — an escape without one is itself a
violation — so every exception stays visible in review.

Usage:
    python scripts/check_native.py                # lint the repo tree
    python scripts/check_native.py FILE [FILE...] # lint specific files
    python scripts/check_native.py --list-rules

Exit codes: 0 clean, 1 violations found, 2 bad invocation / unreadable file.
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import os
import re
import sys

RULES = {
    "abi-barrier": "extern \"C\" functions need a try/catch exception barrier",
    "ptr-arith-bounds": "overflow-prone `p + n * sizeof(T)` bounds compare",
    "thread-catch": "thread entry points need a top-level catch",
    "wire-count-alloc": "allocation on a wire/file-derived count without a bound check",
    "raw-lock": "raw .lock()/.unlock() — use RAII guards",
    "thread-rng": "rand()/srand() — use eg::ThreadRng()",
    "allow-escape": "malformed eg-lint allow escape",
}

ALLOW_RE = re.compile(r"eg-lint:\s*allow\(([\w-]+)\)\s*(.*)")

# Rules owned by scripts/check_contracts.py but waived with the SAME escape
# grammar in the same native files — recognized here so a contract escape is
# not flagged as a typo; check_contracts audits their use/staleness itself.
EXTERNAL_RULES = {
    "abi-parity",
    "wire-parity",
    "ledger-parity",
    "config-parity",
    "guarded-by",
    "artifact-hygiene",
}


@dataclasses.dataclass
class Violation:
    path: str
    line: int  # 1-based
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ---------------------------------------------------------------------------
# Source preparation
# ---------------------------------------------------------------------------


def strip_comments_and_strings(text: str):
    """Blank out comments and string/char literals, preserving line layout.

    Returns (code, allows) where `code` has every comment/literal byte
    replaced by a space (newlines kept, so offsets and line numbers line
    up with the original), and `allows` maps 1-based line number ->
    list of (rule, reason) parsed from eg-lint allow comments.
    """
    out = []
    allows: dict[int, list[tuple[str, str]]] = {}
    i, n = 0, len(text)
    line = 1
    state = "code"  # code | line_comment | block_comment | string | char
    comment_start = 0
    while i < n:
        c = text[i]
        nxt = text[i + 1] if i + 1 < n else ""
        if state == "code":
            if c == "/" and nxt == "/":
                state = "line_comment"
                comment_start = i
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                state = "block_comment"
                comment_start = i
                out.append("  ")
                i += 2
                continue
            if c == '"':
                state = "string"
                out.append(" ")
                i += 1
                continue
            if c == "'":
                state = "char"
                out.append(" ")
                i += 1
                continue
            out.append(c)
        elif state in ("line_comment", "block_comment"):
            ended = False
            if state == "line_comment" and c == "\n":
                ended = True
            elif state == "block_comment" and c == "*" and nxt == "/":
                text_of = text[comment_start : i + 2]
                m = ALLOW_RE.search(text_of)
                if m:
                    allows.setdefault(line, []).append((m.group(1), m.group(2).strip()))
                out.append("  ")
                i += 2
                state = "code"
                continue
            if ended:
                m = ALLOW_RE.search(text[comment_start:i])
                if m:
                    allows.setdefault(line, []).append((m.group(1), m.group(2).strip()))
                out.append("\n")
                state = "code"
            else:
                out.append("\n" if c == "\n" else " ")
        elif state in ("string", "char"):
            if c == "\\":
                out.append("  ")
                i += 2
                continue
            if (state == "string" and c == '"') or (state == "char" and c == "'"):
                state = "code"
            out.append("\n" if c == "\n" else " ")
        if c == "\n":
            line += 1
        i += 1
    if state in ("line_comment", "block_comment"):
        m = ALLOW_RE.search(text[comment_start:])
        if m:
            allows.setdefault(line, []).append((m.group(1), m.group(2).strip()))
    return "".join(out), allows


def line_of(code: str, offset: int) -> int:
    return code.count("\n", 0, offset) + 1


# ---------------------------------------------------------------------------
# Block / function extraction (brace matching over stripped code)
# ---------------------------------------------------------------------------

CONTROL_KEYWORDS = ("if", "else", "for", "while", "switch", "do", "try", "catch")

FUNC_TAIL_RE = re.compile(
    r"\)\s*(?:const|noexcept|override|final|mutable|->\s*[\w:<>,&*\s]+)?\s*$"
)
LAMBDA_TAIL_RE = re.compile(
    r"\[[^\[\]]*\]\s*(?:\([^()]*\))?\s*(?:mutable|noexcept)?\s*(?:->\s*[\w:<>,&*\s]+)?\s*$"
)


@dataclasses.dataclass
class Block:
    kind: str  # extern | namespace | type | function | lambda | control | block
    name: str
    header_line: int  # line of the text introducing the block
    start: int  # offset of the opening brace
    end: int = -1  # offset of the closing brace
    parents: tuple = ()  # kinds of enclosing blocks, outermost first


def classify_header(header: str) -> tuple[str, str]:
    h = header.strip()
    # string literals are blanked by strip_comments_and_strings, so an
    # `extern "C"` block header survives as a bare `extern`
    if re.match(r"extern\b", h) and "(" not in h:
        return "extern", ""
    if re.match(r"namespace\b", h):
        return "namespace", h.split()[-1] if len(h.split()) > 1 else ""
    if re.match(r"(class|struct|enum|union)\b", h) and "(" not in h:
        m = re.match(r"(?:class|struct|enum(?:\s+class)?|union)\s+(\w+)", h)
        return "type", m.group(1) if m else ""
    first_word = re.match(r"[A-Za-z_]\w*", h)
    if first_word and first_word.group(0) in CONTROL_KEYWORDS:
        return "control", first_word.group(0)
    if LAMBDA_TAIL_RE.search(h):
        return "lambda", ""
    if FUNC_TAIL_RE.search(h) and "(" in h:
        # function definition: name is the identifier before the first
        # paren at depth 0 of the header's own parens
        m = re.search(r"([~\w:]+)\s*\(", h)
        return "function", (m.group(1) if m else "")
    return "block", ""


def extract_blocks(code: str) -> list[Block]:
    """Return all braced blocks with kind classification and extents."""
    blocks: list[Block] = []
    stack: list[Block] = []
    header_start = 0
    i, n = 0, len(code)
    while i < n:
        c = code[i]
        if c in ";":
            header_start = i + 1
        elif c == "{":
            header = code[header_start:i]
            kind, name = classify_header(header)
            # header line: the first non-blank line of the header, else
            # the line of the brace itself
            stripped_off = header_start + (len(header) - len(header.lstrip()))
            hline = line_of(code, stripped_off if header.strip() else i)
            blk = Block(
                kind,
                name,
                hline,
                i,
                parents=tuple(b.kind for b in stack),
            )
            stack.append(blk)
            blocks.append(blk)
            header_start = i + 1
        elif c == "}":
            if stack:
                stack.pop().end = i
            header_start = i + 1
        i += 1
    return blocks


# ---------------------------------------------------------------------------
# Individual rules
# ---------------------------------------------------------------------------

CMP_RE = re.compile(r"(?<![-<>=!+*/|&^])(?:<=|>=|<|>)(?![<>=])")


def strip_templates(line: str) -> str:
    """Blank template argument lists so `static_cast<size_t>(n)` and
    `std::max<int32_t>(a, b)` don't read as comparisons.

    Only spans whose `<` directly follows an identifier character and whose
    contents are type-ish (word chars, ::, commas, *, nested <>) are
    blanked — `a < b && c > d` is left alone.
    """
    out = list(line)
    i = 0
    while i < len(line):
        if line[i] == "<" and i > 0 and (line[i - 1].isalnum() or line[i - 1] == "_"):
            depth = 1
            j = i + 1
            ok = True
            while j < len(line) and depth:
                c = line[j]
                if c == "<":
                    depth += 1
                elif c == ">":
                    depth -= 1
                elif not (c.isalnum() or c in "_:, *\t"):
                    ok = False
                    break
                j += 1
            if ok and depth == 0:
                for k in range(i, j):
                    out[k] = " "
                i = j
                continue
        i += 1
    return "".join(out)
# `... + n * sizeof(T)` and `... n * sizeof(T) + ...` inside a compare
PTR_SUM_A = re.compile(r"\+\s*\(?\s*[\w.\[\]]+(?:->\w+)?\s*\)?\s*\*\s*sizeof\s*\(")
PTR_SUM_B = re.compile(r"\*\s*sizeof\s*\([^)]*\)\s*\+")


def rule_ptr_arith_bounds(path, code_lines, report):
    for ln, text in enumerate(code_lines, 1):
        if "sizeof" not in text:
            continue
        if (PTR_SUM_A.search(text) or PTR_SUM_B.search(text)) and CMP_RE.search(text):
            report(
                ln,
                "ptr-arith-bounds",
                "overflow-prone bounds compare: `p + n * sizeof(T)` wraps for "
                "corrupt huge n — compare n against remaining()/sizeof(T) instead "
                "(see eg::ByteCursor)",
            )


RAW_LOCK_RE = re.compile(r"(?:\.|->)\s*(lock|unlock)\s*\(\s*\)")


def rule_raw_lock(path, code_lines, report):
    for ln, text in enumerate(code_lines, 1):
        m = RAW_LOCK_RE.search(text)
        if m:
            report(
                ln,
                "raw-lock",
                f"raw .{m.group(1)}() — use std::lock_guard/std::unique_lock so "
                "early returns and exceptions cannot leak the mutex",
            )


RAND_RE = re.compile(r"(?<![\w:])(s?rand)\s*\(")


def rule_thread_rng(path, code_lines, report):
    for ln, text in enumerate(code_lines, 1):
        m = RAND_RE.search(text)
        if m:
            report(
                ln,
                "thread-rng",
                f"{m.group(1)}() is process-global and racy under the parallel "
                "samplers — use eg::ThreadRng()",
            )


TRY_RE = re.compile(r"\btry\b")
CATCH_RE = re.compile(r"\bcatch\b|EG_API_GUARD")


def rule_abi_barrier(path, code, blocks, report):
    for blk in blocks:
        if blk.kind != "function" or "extern" not in blk.parents:
            continue
        body = code[blk.start : blk.end + 1] if blk.end >= 0 else code[blk.start :]
        if TRY_RE.search(body) and CATCH_RE.search(body):
            continue
        report(
            blk.header_line,
            "abi-barrier",
            f"extern \"C\" function `{blk.name}` has no try/catch barrier — an "
            "exception crossing the C ABI is std::terminate (SIGABRT) for the "
            "host process",
        )


THREAD_SITE_RE = re.compile(r"std::thread\s*[({]")
THREAD_VEC_RE = re.compile(r"std::vector\s*<\s*std::thread\s*>\s+(\w+)")


def rule_thread_catch(path, code, report):
    sites = [(m.start(), "std::thread") for m in THREAD_SITE_RE.finditer(code)]
    vec_names = set(THREAD_VEC_RE.findall(code))
    for name in vec_names:
        for m in re.finditer(r"\b%s\s*\.\s*emplace_back\s*\(" % re.escape(name), code):
            sites.append((m.start(), f"{name}.emplace_back"))
    for off, what in sorted(sites):
        ln = line_of(code, off)
        # find the lambda argument: first '[' after the call opener
        open_idx = code.find("(", off)
        if open_idx < 0:
            open_idx = code.find("{", off)
        seg = code[open_idx + 1 : open_idx + 200] if open_idx >= 0 else ""
        stripped = seg.lstrip()
        if what == "std::thread" and (not stripped or stripped[0] != "["):
            if not stripped or stripped[0] == ")":
                continue  # declaration like `std::thread t;` / member decl
            report(
                ln,
                "thread-catch",
                "thread entry is not an inline lambda — wrap the callable in a "
                "lambda with a top-level catch so an exception cannot "
                "std::terminate the process",
            )
            continue
        if what != "std::thread" and (not stripped or stripped[0] != "["):
            report(
                ln,
                "thread-catch",
                "thread entry is not an inline lambda — wrap the callable in a "
                "lambda with a top-level catch",
            )
            continue
        lam_start = open_idx + 1 + (len(seg) - len(stripped))
        # skip capture list, optional params/specifiers, find body brace
        cap_end = code.find("]", lam_start)
        if cap_end < 0:
            continue
        j = cap_end + 1
        depth = 0
        body_start = -1
        while j < len(code):
            ch = code[j]
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "{" and depth == 0:
                body_start = j
                break
            elif ch == ";" and depth == 0:
                break
            j += 1
        if body_start < 0:
            continue
        depth = 0
        k = body_start
        while k < len(code):
            if code[k] == "{":
                depth += 1
            elif code[k] == "}":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        body = code[body_start : k + 1]
        if not re.search(r"\bcatch\b", body):
            report(
                ln,
                "thread-catch",
                "thread entry lambda has no top-level catch — an exception "
                "escaping a thread entry is std::terminate for the whole "
                "process",
            )


# wire/file-derived scalar reads that taint a variable
TAINT_RES = [
    re.compile(r"\b(\w+)\s*=\s*[\w.]*(?:->)?\s*(?:I32|I64|U64|U8|F32|Pod(?:<[^;=]*>)?)\s*\(\s*\)"),
    re.compile(r"\bRead\s*\(\s*&(\w+)\s*\)"),
    re.compile(r"\bmemcpy\s*\(\s*&(\w+)\s*,"),
    re.compile(r"\b(\w+)\s*=\s*\w+\.tellg\s*\(\s*\)"),
]
ALLOC_SINK_RES = [
    re.compile(r"(?:\.|->)\s*(?:resize|reserve)\s*\(([^;]*)\)"),
    re.compile(r"\bnew\s+[\w:<>]+\s*\[([^\]]*)\]"),
    re.compile(r"\bstd::(?:vector|string)\s*<[^;=]*>\s+\w+\s*\(([^;]*)\)"),
    re.compile(r"\bstd::string\s+\w+\s*\(([^;]*)\)"),
]
GUARD_NAME_RE = re.compile(r"(?i)\b\w*(oversiz|bound|cap|check|valid|clamp)\w*\s*\(")
MIN_RE = re.compile(r"\bstd::min\b")


def rule_wire_count_alloc(path, code, blocks, report):
    funcs = [b for b in blocks if b.kind == "function" and b.end >= 0]
    for blk in funcs:
        # skip functions that contain nested functions (shouldn't happen in C++)
        body = code[blk.start : blk.end + 1]
        base_line = line_of(code, blk.start)
        tainted: dict[str, int] = {}
        for off_ln, text in enumerate(body.split("\n")):
            ln = base_line + off_ln
            # guards first: any comparison or bound-ish call naming the var
            # (template args blanked so casts don't read as comparisons)
            cleaned = strip_templates(text)
            for var in list(tainted):
                if re.search(r"\b%s\b" % re.escape(var), cleaned) and (
                    CMP_RE.search(cleaned)
                    or GUARD_NAME_RE.search(cleaned)
                    or MIN_RE.search(cleaned)
                ):
                    del tainted[var]
            for sink in ALLOC_SINK_RES:
                for m in sink.finditer(text):
                    arg = m.group(1)
                    for var, src_ln in tainted.items():
                        if re.search(r"\b%s\b" % re.escape(var), arg):
                            report(
                                ln,
                                "wire-count-alloc",
                                f"allocation sized by `{var}` (wire/file-derived "
                                f"at line {src_ln}) with no preceding bound "
                                "check — a hostile count forces a huge "
                                "allocation before any data is validated",
                            )
            for taint in TAINT_RES:
                for m in taint.finditer(text):
                    tainted[m.group(1)] = ln


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def lint_text(text: str, path: str, rules=None, stale_out=None) -> list[Violation]:
    """Lint one file's text. With `stale_out` (a list), every eg-lint escape
    for a rule owned by THIS linter that did not suppress any violation is
    appended to it as a Violation — the `--escapes` staleness audit."""
    code, allows = strip_comments_and_strings(text)
    code_lines = code.split("\n")
    blocks = extract_blocks(code)
    violations: list[Violation] = []
    active = set(rules) if rules else set(RULES) - {"allow-escape"}

    used_allows: set[tuple[int, str]] = set()

    def check_allow(cand: int, rule: str) -> bool:
        for arule, reason in allows.get(cand, []):
            if arule == rule:
                used_allows.add((cand, arule))
                if not reason:
                    violations.append(
                        Violation(
                            path,
                            cand,
                            "allow-escape",
                            f"allow({rule}) escape has no reason — justify "
                            "the exception so it is visible in review",
                        )
                    )
                return True
        return False

    def report(ln: int, rule: str, message: str, window: int = 1):
        # suppression: allow(rule) on the line itself, within `window`
        # lines below (function-level rules cover the first body line), or
        # in the run of comment/blank lines directly above
        for cand in range(ln, ln + window + 1):
            if check_allow(cand, rule):
                return
        cand = ln - 1
        while cand >= 1:
            if check_allow(cand, rule):
                return
            if cand <= len(code_lines) and code_lines[cand - 1].strip():
                break  # real code without a matching allow: stop walking
            cand -= 1
        violations.append(Violation(path, ln, rule, message))

    def freport(ln, rule, message):  # function-level: wider window
        report(ln, rule, message, window=2)

    if "ptr-arith-bounds" in active:
        rule_ptr_arith_bounds(path, code_lines, report)
    if "raw-lock" in active:
        rule_raw_lock(path, code_lines, report)
    if "thread-rng" in active:
        rule_thread_rng(path, code_lines, report)
    if "abi-barrier" in active:
        rule_abi_barrier(path, code, blocks, freport)
    if "thread-catch" in active:
        rule_thread_catch(path, code, report)
    if "wire-count-alloc" in active:
        rule_wire_count_alloc(path, code, blocks, report)

    # unknown-rule escapes are themselves violations (typo-proofing)
    for ln, entries in allows.items():
        for arule, _ in entries:
            if arule not in RULES and arule not in EXTERNAL_RULES:
                violations.append(
                    Violation(
                        path,
                        ln,
                        "allow-escape",
                        f"allow({arule}) names an unknown rule "
                        f"(known: {', '.join(sorted(set(RULES) - {'allow-escape'}))})",
                    )
                )
    if stale_out is not None:
        for ln, entries in allows.items():
            for arule, _ in entries:
                if arule in EXTERNAL_RULES or arule not in RULES:
                    continue  # contract-rule escapes audited by check_contracts
                if (ln, arule) not in used_allows:
                    stale_out.append(
                        Violation(
                            path,
                            ln,
                            "allow-escape",
                            f"stale escape: allow({arule}) suppresses nothing "
                            "on this line any more — the waived code is gone "
                            "or the rule no longer fires here; delete the "
                            "escape",
                        )
                    )
    violations.sort(key=lambda v: (v.line, v.rule))
    return violations


def lint_file(path: str, rules=None, stale_out=None) -> list[Violation]:
    with open(path, encoding="utf-8", errors="replace") as f:
        return lint_text(f.read(), path, rules, stale_out=stale_out)


def default_targets(root: str) -> list[str]:
    native = os.path.join(root, "euler_tpu", "graph", "_native")
    files = sorted(
        glob.glob(os.path.join(native, "*.cc")) + glob.glob(os.path.join(native, "*.h"))
    )
    return files


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("paths", nargs="*", help="files to lint (default: the repo's _native tree)")
    ap.add_argument("--rules", help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--escapes",
        action="store_true",
        help="audit every eg-lint allow(...) escape: list each one and flag "
        "stale escapes whose line no longer triggers the waived rule "
        "(exit 1 when any is stale)",
    )
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root for default target discovery",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, desc in RULES.items():
            print(f"{name:18s} {desc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    targets = args.paths or default_targets(args.root)
    if not targets:
        print("no lint targets found", file=sys.stderr)
        return 2

    if args.escapes:
        # staleness needs every rule active: a subset would mark escapes
        # for the disabled rules stale by construction
        if rules:
            print("--escapes ignores --rules (full rule set required)",
                  file=sys.stderr)
        stale: list[Violation] = []
        total = 0
        stale_keys = set()
        for path in targets:
            if not os.path.isfile(path):
                print(f"cannot read {path}", file=sys.stderr)
                return 2
            per_file: list[Violation] = []
            with open(path, encoding="utf-8", errors="replace") as f:
                text = f.read()
            lint_text(text, path, stale_out=per_file)
            stale.extend(per_file)
            stale_keys |= {(v.path, v.line) for v in per_file}
            _, allows = strip_comments_and_strings(text)
            for ln in sorted(allows):
                for arule, reason in allows[ln]:
                    total += 1
                    status = "STALE" if (path, ln) in stale_keys and any(
                        v.line == ln and f"allow({arule})" in v.message
                        for v in per_file
                    ) else ("EXTERNAL" if arule in EXTERNAL_RULES else "USED")
                    print(f"{path}:{ln}: allow({arule}) {status} — {reason}")
        if stale:
            print(f"\n{len(stale)} stale escape(s) of {total}")
            return 1
        print(f"escape audit clean: {total} escape(s), none stale "
              "(EXTERNAL = contract rule, audited by check_contracts.py)")
        return 0

    all_violations: list[Violation] = []
    for path in targets:
        if not os.path.isfile(path):
            print(f"cannot read {path}", file=sys.stderr)
            return 2
        all_violations.extend(lint_file(path, rules))

    for v in all_violations:
        print(v)
    nfiles = len(targets)
    if all_violations:
        print(f"\n{len(all_violations)} violation(s) in {nfiles} file(s)")
        return 1
    print(f"clean: {nfiles} file(s), {len(RULES) - 1} rules")
    return 0


if __name__ == "__main__":
    sys.exit(main())
