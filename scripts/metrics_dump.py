#!/usr/bin/env python3
"""Scrape a running euler_tpu cluster's telemetry and pretty-print it.

Connects a remote client to a live cluster (registry dir, tcp://
registry, or an explicit shard list), scrapes every shard over the
STATS wire opcode (eg_telemetry), and prints per shard:

  * admission gauges — handler pool size, workers busy, queue depth,
    open connections, draining flag (the PR-4 survivability state an
    operator previously had to shell into the host to see);
  * per-op server handler latency: count + p50/p90/p99 µs from the
    log2-bucketed histograms;
  * non-zero counters (FAULTS.md glossary);
  * the shard's slowest spans with their trace ids.

With `--watch N` it re-scrapes every N seconds and prints per-interval
RATES (requests served /s, counter movement /s) next to the live
gauges — the at-a-glance view for watching a rolling restart or a load
drill without a Prometheus stack; `--raw` restores raw cumulative
counter values. A transiently unreachable shard (mid-restart,
crashed, draining) is skipped-and-noted, never aborts the watch; its
deltas resume from the last good scrape once it answers again. Step-phase histograms (OBSERVABILITY.md "Step
phases") print whenever a scraped process has recorded any — shard
services normally haven't (phases live in the training client), but an
in-process cluster or a future co-located trainer shows them here.

Usage:
    python scripts/metrics_dump.py --registry /shared/reg
    python scripts/metrics_dump.py --shards h1:9001,h2:9001
    python scripts/metrics_dump.py --registry /shared/reg --watch 5
    python scripts/metrics_dump.py --registry tcp://host:9100 --json
    python scripts/metrics_dump.py --smoke     # self-contained check
                                               # (spins a tiny 2-shard
                                               # cluster; verify.sh)

See OBSERVABILITY.md for the runbook (watching a rolling restart
through this scrape included).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def dump_cluster(graph, as_json: bool = False) -> list:
    """Scrape every shard; print and return the per-shard dicts."""
    from euler_tpu import telemetry as T

    shards = []
    for s in range(graph.num_shards):
        data = T.scrape(graph, s)
        shards.append(data)
        if as_json:
            continue
        g = data.get("gauges", {})
        print(f"== shard {data['shard']} ==")
        print(
            f"  workers {g.get('workers', '?')}"
            f" busy {g.get('workers_active', '?')}"
            f" queue {g.get('queue_depth', '?')}"
            f" conns {g.get('conns', '?')}"
            f" draining {g.get('draining', '?')}"
            f" epoch {g.get('epoch', 0)}"
        )
        rows = [
            (key.split(":", 1)[1], h)
            for key, h in sorted(data["hist"].items())
            if key.startswith("server_handler:") and h["count"] > 0
        ]
        if rows:
            print(f"  {'op':22s} {'count':>8s} {'p50_us':>10s} "
                  f"{'p90_us':>10s} {'p99_us':>10s}")
            for op, h in rows:
                pct = T.percentiles(h)
                print(f"  {op:22s} {h['count']:8d} {pct[50]:10.1f} "
                      f"{pct[90]:10.1f} {pct[99]:10.1f}")
        else:
            print("  no handler latency samples yet")
        ph_rows = [
            (key.split(":", 1)[1], h)
            for key, h in sorted(data["hist"].items())
            if key.startswith("phase:") and h["count"] > 0
        ]
        if ph_rows:
            print(f"  {'phase':22s} {'count':>8s} {'p50_us':>10s} "
                  f"{'p90_us':>10s} {'p99_us':>10s}")
            for ph, h in ph_rows:
                pct = T.percentiles(h)
                print(f"  {ph:22s} {h['count']:8d} {pct[50]:10.1f} "
                      f"{pct[90]:10.1f} {pct[99]:10.1f}")
        sv_rows = [
            (key.split(":", 1)[1], h)
            for key, h in sorted(data["hist"].items())
            if key.startswith("serve:") and h["count"] > 0
        ]
        if sv_rows:
            print(f"  {'serve':22s} {'count':>8s} {'p50_us':>10s} "
                  f"{'p90_us':>10s} {'p99_us':>10s}")
            for ph, h in sv_rows:
                pct = T.percentiles(h)
                print(f"  {ph:22s} {h['count']:8d} {pct[50]:10.1f} "
                      f"{pct[90]:10.1f} {pct[99]:10.1f}")
        sb = data["hist"].get("serve_batch", {})
        if sb.get("count"):
            print(f"  serve_batch: {sb['count']} dispatches, "
                  f"{sb['sum_us'] / sb['count']:.1f} unique ids/dispatch")
        nonzero = {k: v for k, v in data["counters"].items() if v}
        if nonzero:
            print(f"  counters: {nonzero}")
        # device plane (OBSERVABILITY.md "Device plane"): compile
        # economics + memory high-water + transfer volume, whenever the
        # scraped process has recorded any
        res = data.get("resource", {})
        c = data["counters"]
        if (c.get("device_compiles") or c.get("device_recompiles")
                or res.get("device_mem_peak_bytes")):
            ch = data["hist"].get("phase:compile") or {"count": 0,
                                                       "sum_us": 0}
            print(
                f"  device: {c.get('device_compiles', 0)} compiles "
                f"({ch['sum_us'] / 1000.0:.0f} ms), "
                f"{c.get('device_recompiles', 0)} recompiles, "
                f"{c.get('serve_recompiles', 0)} serve recompiles, "
                f"mem {res.get('device_mem_bytes', 0) / 1e6:.1f}MB "
                f"(peak {res.get('device_mem_peak_bytes', 0) / 1e6:.1f}MB"
                f", {res.get('device_buffers', 0)} buffers), "
                f"h2d {c.get('h2d_bytes', 0) / 1e6:.1f}MB "
                f"d2h {c.get('d2h_bytes', 0) / 1e6:.1f}MB"
            )
        for sp in data["slow_spans"][:5]:
            print(f"  slow: {sp['op']:20s} {sp['total_us']:>9d}us "
                  f"queue={sp['queue_us']} handler={sp['handler_us']} "
                  f"wire={sp['wire_us']} outcome={sp['outcome']} "
                  f"trace={int(sp['trace']):#x}")
    if as_json:
        print(json.dumps(shards))
    return shards


def _served_total(data: dict) -> int:
    return sum(
        h["count"] for key, h in data["hist"].items()
        if key.startswith("server_handler:")
    )


def watch_cluster(graph, every_s: float, iterations: int | None = None,
                  out=sys.stdout, raw: bool = False) -> None:
    """Re-scrape every `every_s` seconds, printing per-shard RATES
    (requests served /s and counter movement /s over the interval since
    the shard's last good scrape) next to the live admission gauges —
    a cumulative counter's absolute value says nothing at a glance; its
    rate is what an operator watches move. `raw=True` (--raw) prints
    the raw cumulative counter values instead. iterations=None runs
    until interrupted (the CLI); tests pass a bound."""
    from euler_tpu import telemetry as T

    prev: dict = {}
    unreachable: set = set()
    n = 0
    while iterations is None or n < iterations:
        if n:
            time.sleep(every_s)
        stamp = time.strftime("%H:%M:%S")
        for s in range(graph.num_shards):
            try:
                data = T.scrape(graph, s)
            except Exception as e:
                # a transiently unreachable shard is ROUTINE during a
                # rolling restart (DEPLOY.md drill): skip-and-note, keep
                # watching the rest — the watch must outlive the blip.
                # prev[s] is kept, so rates resume from the last good
                # scrape when the shard comes back.
                unreachable.add(s)
                print(f"[{stamp}] shard {s}: unreachable — skipped "
                      f"({type(e).__name__}: {e})", file=out)
                continue
            if s in unreachable:
                unreachable.discard(s)
                print(f"[{stamp}] shard {s}: reachable again", file=out)
            now = time.monotonic()
            served = _served_total(data)
            ctr = {k: v for k, v in data["counters"].items() if v}
            last = prev.get(s, {})
            dt = now - last.get("t", now)
            d_served = served - last.get("served", 0)
            d_ctr = {
                k: v - last.get("ctr", {}).get(k, 0)
                for k, v in ctr.items()
            }
            d_ctr = {k: v for k, v in d_ctr.items() if v}
            # transfer volume reads as a bandwidth, not a raw delta
            h2d, d2h = d_ctr.pop("h2d_bytes", 0), d_ctr.pop("d2h_bytes", 0)
            g = data.get("gauges", {})
            line = (f"[{stamp}] shard {s}: served +{d_served}"
                    f"{_rate(d_served, dt)} "
                    f"busy {g.get('workers_active', '?')} "
                    f"queue {g.get('queue_depth', '?')} "
                    f"conns {g.get('conns', '?')} "
                    f"draining {g.get('draining', '?')} "
                    # current serving snapshot epoch (eg_epoch.h): during
                    # a rolling graph refresh (DEPLOY.md) the operator
                    # watches this tick up shard by shard
                    f"epoch {g.get('epoch', 0)}")
            res = data.get("resource", {})
            if res.get("device_mem_peak_bytes"):
                line += (f" dev_mem {res.get('device_mem_bytes', 0) / 1e6:.0f}"
                         f"/{res['device_mem_peak_bytes'] / 1e6:.0f}MB")
            if not raw and (h2d or d2h) and dt > 0:
                line += (f" h2d {h2d / dt / 1e6:.1f}MB/s "
                         f"d2h {d2h / dt / 1e6:.1f}MB/s")
            # input-stall rate: ROADMAP item 1's acceptance metric as a
            # live column — a shared-mode training process exposes its
            # phase hists in the same scrape; mean stall per step over
            # THIS interval, so a pipeline losing the race shows up as
            # the number moving, not as a diluted lifetime mean
            ih = data.get("hist", {}).get("phase:input_stall")
            if not raw and ih and ih.get("count"):
                lst = last.get("stall", {"count": 0, "sum_us": 0})
                dc = ih["count"] - lst["count"]
                ds = ih["sum_us"] - lst["sum_us"]
                if dc > 0:
                    line += (f" input_stall {ds / dc / 1000:.2f}ms/step"
                             f"{_rate(dc, dt)}")
            if raw:
                if ctr:
                    line += f"  counters {ctr}"
            elif d_ctr:
                rates = {
                    k: round(v / dt, 1) if dt > 0 else float(v)
                    for k, v in d_ctr.items()
                }
                line += f"  Δcounters/s {rates}"
            print(line, file=out)
            prev[s] = {
                "served": served, "ctr": ctr, "t": now,
                "stall": {
                    "count": ih["count"] if ih else 0,
                    "sum_us": ih["sum_us"] if ih else 0,
                },
            }
        out.flush()
        n += 1


def _rate(delta: int, dt: float) -> str:
    """Render ' (N/s)' for a per-interval delta; empty on the first
    scrape of a shard (no interval to rate over yet)."""
    if dt <= 0:
        return ""
    return f" ({delta / dt:.1f}/s)"


def run_smoke() -> int:
    """Self-contained scrape check: tiny 2-shard in-process cluster,
    a little traffic, then assert the scrape agrees with the wire's
    ground truth (verify.sh gate)."""
    import shutil
    import tempfile

    import euler_tpu
    from euler_tpu import telemetry as T
    from euler_tpu.graph.service import GraphService

    sys.path.insert(0, REPO)
    from scripts.remote_bench import build_powerlaw_fixture

    tmp = tempfile.mkdtemp(prefix="euler_metrics_smoke_")
    svcs = []
    try:
        data = os.path.join(tmp, "data")
        os.makedirs(data)
        build_powerlaw_fixture(data, 120, 6, 8)
        svcs = [GraphService(data, s, 2) for s in range(2)]
        g = euler_tpu.Graph(
            mode="remote", shards=[s.address for s in svcs],
            retries=2, timeout_ms=2000,
        )
        try:
            T.telemetry_reset()
            steps = 4
            for _ in range(steps):
                roots = g.sample_node(16, -1)
                g.sample_fanout(roots, [[0, 1], [0, 1]], [3, 3])
                g.get_dense_feature(roots, [0], [8])
            shards = dump_cluster(g)
            assert len(shards) == 2, shards
            for data_s in shards:
                assert "gauges" in data_s and data_s["gauges"]["workers"] > 0
                served = sum(
                    h["count"] for key, h in data_s["hist"].items()
                    if key.startswith("server_handler:")
                )
                assert served > 0, data_s["hist"]
            # in-process shards: the scrape and the local dump read the
            # same globals — numbers must be identical where the scrape
            # itself doesn't add samples (the stats op records AFTER its
            # reply is built, so compare a family the scrape never touches)
            local = T.telemetry_json()["hist"]
            for data_s in shards[-1:]:
                key = "server_handler:sample_node"
                assert data_s["hist"][key]["b"] == local[key]["b"], key
            # client side saw every op too
            spans = T.slow_spans()
            assert spans and any(s["side"] == "client" for s in spans)
            # the --watch rate path against the same live cluster
            # (after the parity pins — watching adds scrape traffic):
            # a second interval must carry /s rates (the first scrape
            # of a shard has no interval to rate over), and --raw must
            # fall back to cumulative counter values
            import io

            # in-process shards share the client's phase globals, so a
            # recorded stall must surface as the input_stall column
            # (the live view of the sampler_depth pipeline's race)
            T.record_phase("input_stall", 1500)
            buf = io.StringIO()
            watch_cluster(g, 0.05, iterations=2, out=buf)
            watch_out = buf.getvalue()
            assert "served +" in watch_out, watch_out
            assert "/s)" in watch_out, watch_out
            assert " epoch 0" in watch_out, watch_out  # per-shard column
            assert "input_stall 1.50ms/step" in watch_out, watch_out
            buf_raw = io.StringIO()
            watch_cluster(g, 0.05, iterations=1, out=buf_raw, raw=True)
            raw_out = buf_raw.getvalue()
            assert "Δcounters/s" not in raw_out, raw_out
            print("metrics_dump smoke: OK")
            return 0
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--registry", default="", help=(
        "registry dir or tcp://host:port the cluster registered with"))
    ap.add_argument("--shards", default="", help=(
        "explicit comma-separated host:port shard list"))
    ap.add_argument("--timeout_ms", type=int, default=3000)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: one JSON array of shard dumps")
    ap.add_argument("--watch", type=float, default=0.0, metavar="N", help=(
        "re-scrape every N seconds, printing per-shard RATES (requests "
        "served /s, counter movement /s over each interval) next to "
        "the live gauges; Ctrl-C stops"))
    ap.add_argument("--raw", action="store_true", help=(
        "with --watch: print raw cumulative counter values instead of "
        "per-interval rates"))
    ap.add_argument("--iterations", type=int, default=None,
                    help=argparse.SUPPRESS)  # bounds --watch (tests)
    ap.add_argument("--smoke", action="store_true", help=(
        "spin a tiny local 2-shard cluster and assert the scrape "
        "(the verify.sh gate)"))
    args = ap.parse_args()

    if args.smoke:
        return run_smoke()
    if not args.registry and not args.shards:
        ap.error("need --registry or --shards (or --smoke)")

    import euler_tpu

    g = euler_tpu.Graph(
        mode="remote",
        registry=args.registry or None,
        shards=args.shards.split(",") if args.shards else None,
        retries=2,
        timeout_ms=args.timeout_ms,
        rediscover_ms=0,
    )
    try:
        if args.watch > 0:
            try:
                watch_cluster(g, args.watch, iterations=args.iterations,
                              raw=args.raw)
            except KeyboardInterrupt:
                pass
        else:
            dump_cluster(g, as_json=args.json)
    finally:
        g.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
