#!/bin/sh
# Event-driven chip-window capture. The relay to the single real TPU
# wedges for hours and returns without warning (BENCH_NOTES wedge
# post-mortems: it has died mid-session, between a successful probe and
# the next backend init, and right after a green suite) — so on-chip
# work must fire the moment a window opens, not when an operator
# happens to look. Probe every INTERVAL seconds (default 240: the one
# observed window this round lasted ~25 min and a probe cycle costs
# ≤170 s, so a 600 s sleep could eat half a window) in a killable
# subprocess; on the first success run CMD once and exit with its
# status. Start it detached at session start:
#
#   nohup setsid sh scripts/chip_watcher.sh >/tmp/chip_watcher.log 2>&1 &
#
# With no CMD, runs the full evidence queue (EULER_TPU_SWEEP=1
# scripts/tpu_checks.sh). Every probe is timestamped so the log doubles
# as the relay-availability record for the session. The interpreter is
# ${PYTHON:-python3}; a probe that fails to LAUNCH (missing interpreter,
# import error) is fatal, not logged as "relay still wedged" — a watcher
# that can never probe must die loudly instead of looping forever.
#   sh scripts/chip_watcher.sh [-i seconds] [cmd...]
cd "$(dirname "$0")/.." || exit 1
PYTHON="${PYTHON:-python3}"
INTERVAL=240
if [ "$1" = "-i" ]; then
  if [ -z "$2" ]; then
    echo "chip_watcher: -i needs a seconds argument" >&2
    exit 2
  fi
  case "$2" in
    ''|*[!0-9]*)
      echo "chip_watcher: -i wants a positive integer, got '$2'" >&2
      exit 2
      ;;
  esac
  INTERVAL="$2"
  shift 2
fi

while :; do
  # probe exit codes: 0 = window open, 3 = relay down (probe RAN and said
  # no), 124/137 = probe timed out (wedged relay). Anything else means the
  # probe itself could not run (127 interpreter missing, 1 import error,
  # ...) — fatal, because retrying would log "wedged" forever while never
  # actually probing.
  timeout -k 10 170 "$PYTHON" -c "
import sys
from euler_tpu.parallel import probe_backend_once
p, err = probe_backend_once(150)
print('probe:', p or err, flush=True)
sys.exit(0 if p else 3)
"
  rc=$?
  case "$rc" in
    0)
      echo "chip_watcher: probe succeeded at $(date -u +%H:%M:%S) — running queue" >&2
      if [ "$#" -gt 0 ]; then
        "$@"
      else
        EULER_TPU_SWEEP=1 sh scripts/tpu_checks.sh
      fi
      exit $?
      ;;
    3|124|137)
      echo "chip_watcher: $(date -u +%H:%M:%S) relay still wedged (rc=$rc); next probe in ${INTERVAL}s" >&2
      ;;
    *)
      echo "chip_watcher: probe failed to launch (rc=$rc, interpreter=$PYTHON) — fix the environment, not the relay" >&2
      exit 1
      ;;
  esac
  sleep "$INTERVAL"
done
