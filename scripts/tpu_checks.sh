#!/bin/sh
# Everything that needs the real chip, in EVIDENCE-VALUE order — the
# relay can wedge at any moment mid-window, so the scarcest artifacts
# run first:
#  1. the benchmarks in ONE bench.py run: reddit + the bf16 A/B + the
#     ppi headline (device-sampling scan loop, kernel on/off A/B,
#     prefetch-overlap breakdown, profiler trace), PLUS the real-degree
#     heavy-tail config (113.7M-edge power-law, exact alias device
#     sampling) when its ~2 GB graph cache is already built WITH
#     current params (scripts/reddit_heavytail.py --full builds it; a
#     stale or absent cache skips the config rather than paying the
#     rebuild on a chip window). Every config runs in its own killable
#     subprocess banking its JSON to .bench_bank/ the moment it exists,
#     so a wedge costs one config, not the window.
#  2. the TPU-gated Pallas kernel suite (distribution pinning vs the
#     host engine, OOB clamp, wide-slab register-boundary draw, the
#     chained two-hop kernel, both shard_map SPMD paths) plus the
#     alias-sampler suite and the exact rejection-walk suite
#     (distribution vs the analytic node2vec target) on the real
#     backend.
#  3. (EULER_TPU_SWEEP=1) the batch-scaling sweep.
# CPU-only environments: bench falls back with an "error" field; the
# kernel suite skips itself. Safe to run unattended: every step has a
# hard deadline and unbuffered output — the relay has been observed to
# wedge AFTER a successful probe (2026-07-31: pytest blocked 19 min in
# backend init with zero CPU accumulation), and a silent hang must
# surface as a visible timeout, not eat the session. Exit code 124
# from a step means the deadline hit (relay wedged mid-run). A suite
# failure no longer aborts the later steps: each step self-protects,
# and on a flaky relay "some banked evidence" beats "clean abort".
cd "$(dirname "$0")/.." || exit 1
SUITE_DEADLINE=${EULER_TPU_SUITE_DEADLINE:-1200}

# Persistent XLA compilation cache: chip windows are scarce and the
# first TPU compile of each program costs 20-40 s — a second window
# (or the suite after the bench) reuses compiles instead of repaying
# them. Harmless on CPU fallback.
JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-"$(pwd)/.jax_cache"}
export JAX_COMPILATION_CACHE_DIR
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-2}

# --- step 1: bench (one bench.py invocation for every config — a
# second process would pay the backend probe cycle twice on the scarce
# window). The heavytail config joins only when its cache is FINISHED
# with CURRENT params — datasets.powerlaw_cache_ready shares the params
# constructor with the builder, so this gate cannot drift from what
# _cache_begin would accept (a bare done-marker check would wave
# through a stale cache and trigger the full rebuild mid-window).
CFGS="reddit,reddit_bf16,ppi"
BENCH_BASE=3000
# the gate gets its own deadline like every other step (a site hook
# can pre-register a backend at interpreter start; a hang here would
# otherwise silently eat the window before bench even begins) — a
# timed-out gate counts as "cache not ready"
if timeout -k 10 60 python -c "
import sys
from euler_tpu.datasets import (
    REDDIT_HEAVYTAIL, heavytail_cache_dir, powerlaw_cache_ready,
)
sys.exit(
    0 if powerlaw_cache_ready(heavytail_cache_dir(), **REDDIT_HEAVYTAIL)
    else 1
)
"; then
  CFGS="reddit_heavytail,$CFGS"
  # four configs need headroom beyond the three-config default; the
  # --deadline flag (unlike the EULER_TPU_BENCH_DEADLINE env var, which
  # is honored as-is) keeps bench.py's x3 CPU-fallback scaling, so a
  # slow-but-healthy CPU run is not misreported as a backend hang
  BENCH_BASE=4800
fi

# bench.py runs every config in its own killable subprocess and banks
# each JSON result to .bench_bank/ the moment it exists, so a mid-run
# relay wedge costs at most one config. The parent never touches the
# backend itself, but back it with an external deadline strictly beyond
# its worst case (x3 CPU scaling) anyway (-u so partial JSON lines land
# either way). An operator-set EULER_TPU_BENCH_DEADLINE is honored
# as-is by bench.py (no CPU scaling, no --deadline flag overriding it).
if [ -n "$EULER_TPU_BENCH_DEADLINE" ]; then
  BENCH_T="$((EULER_TPU_BENCH_DEADLINE + 300))"
  timeout -k 30 "$BENCH_T" python -u bench.py --configs "$CFGS"
else
  BENCH_T="$((3 * BENCH_BASE + 300))"
  timeout -k 30 "$BENCH_T" python -u bench.py --configs "$CFGS" \
    --deadline "$BENCH_BASE"
fi
bench_rc=$?
if [ "$bench_rc" -eq 124 ] || [ "$bench_rc" -eq 137 ]; then
  echo "tpu_checks: BENCH external deadline hit — backend wedged in a GIL-holding native call" >&2
fi

# --- step 2: the on-hardware suites
EULER_TPU_TESTS_ON_TPU=1 timeout -k 30 "$SUITE_DEADLINE" \
  python -u -m pytest tests/test_pallas_sampling.py \
  tests/test_alias_sampling.py tests/test_alias_walk.py -v
suite_rc=$?
# 124 = SIGTERM honored; 137 = the wedged-in-device-wait mode ignores
# SIGTERM and eats the -k 30 SIGKILL instead — both are the deadline
if [ "$suite_rc" -eq 124 ] || [ "$suite_rc" -eq 137 ]; then
  echo "tpu_checks: SUITE DEADLINE (${SUITE_DEADLINE}s) hit — relay wedged mid-run" >&2
fi

# --- step 3 (optional): batch-scaling sweep (EULER_TPU_SWEEP=1) — the
# throughput-optimal operating point for PERF.md's batch/MFU curve.
# Per-point results bank to .bench_bank/sweep.jsonl as they complete;
# failures never mask the bench/suite exit code.
if [ "$EULER_TPU_SWEEP" = "1" ]; then
  # reddit_heavytail sweeps only when its cache is ready (the script
  # gates itself and records a skip line otherwise). External deadline
  # covers the per-config caps in the WORST (CPU-fallback x3) case:
  # 3x(900 + 900) for ppi+reddit plus the heavytail skip, with slack —
  # a healthy TPU run (900 + 900 + 2400) finishes far earlier.
  timeout -k 30 8400 python -u scripts/batch_sweep.py \
    --configs ppi,reddit,reddit_heavytail || \
    echo "tpu_checks: sweep step failed (bench/suite rc preserved)" >&2
fi

# bench is the scarce driver-facing artifact; its failure outranks the
# suite's, but a green bench with a red suite still fails the script
if [ "$bench_rc" -ne 0 ]; then
  exit "$bench_rc"
fi
exit "$suite_rc"
