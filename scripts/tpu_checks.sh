#!/bin/sh
# Everything that needs the real chip, in dependency order:
#  1. the TPU-gated Pallas kernel suite (distribution pinning vs the host
#     engine, OOB clamp, wide-slab register-boundary draw)
#  2. the headline benchmark (device-sampling scan loop, kernel on/off
#     A/B on the ppi config, prefetch-overlap breakdown, profiler trace)
# CPU-only environments: the kernel suite skips itself; bench falls back
# with an "error" field. Safe to run unattended (probe subprocesses are
# killable; the bench has a hang watchdog).
set -e
cd "$(dirname "$0")/.."
EULER_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_pallas_sampling.py -v
python bench.py
