#!/bin/sh
# Everything that needs the real chip, in dependency order:
#  1. the TPU-gated Pallas kernel suite (distribution pinning vs the host
#     engine, OOB clamp, wide-slab register-boundary draw, the chained
#     two-hop kernel, both shard_map SPMD paths) plus the alias-sampler
#     suite on the real backend
#  2. the headline benchmark (device-sampling scan loop, kernel on/off
#     A/B on the ppi config, prefetch-overlap breakdown, profiler trace)
# CPU-only environments: the kernel suite skips itself; bench falls back
# with an "error" field. Safe to run unattended: every step has a hard
# deadline and unbuffered output — the relay has been observed to wedge
# AFTER a successful probe (2026-07-31: pytest blocked 19 min in backend
# init with zero CPU accumulation), and a silent hang must surface as a
# visible timeout, not eat the session. Exit code 124 from a step means
# the deadline hit (relay wedged mid-run).
cd "$(dirname "$0")/.." || exit 1
SUITE_DEADLINE=${EULER_TPU_SUITE_DEADLINE:-1200}

EULER_TPU_TESTS_ON_TPU=1 timeout -k 30 "$SUITE_DEADLINE" \
  python -u -m pytest tests/test_pallas_sampling.py \
  tests/test_alias_sampling.py -v
suite_rc=$?
# 124 = SIGTERM honored; 137 = the wedged-in-device-wait mode ignores
# SIGTERM and eats the -k 30 SIGKILL instead — both are the deadline
if [ "$suite_rc" -eq 124 ] || [ "$suite_rc" -eq 137 ]; then
  echo "tpu_checks: SUITE DEADLINE (${SUITE_DEADLINE}s) hit — relay wedged mid-run" >&2
fi
[ "$suite_rc" -eq 0 ] || exit "$suite_rc"

# bench.py carries its own probe subprocesses + in-process watchdog
# (EULER_TPU_BENCH_DEADLINE, default 2400 s, x3 on CPU fallback) — but
# that watchdog is a Python daemon thread, and the post-probe wedge
# mode can block a native call that never yields the GIL, so back it
# with an external deadline strictly beyond the watchdog's worst case
# (-u so partial JSON lines land either way)
timeout -k 30 "$((3 * ${EULER_TPU_BENCH_DEADLINE:-2400} + 300))" \
  python -u bench.py
bench_rc=$?
if [ "$bench_rc" -eq 124 ] || [ "$bench_rc" -eq 137 ]; then
  echo "tpu_checks: BENCH external deadline hit — backend wedged in a GIL-holding native call" >&2
fi
exit "$bench_rc"
