"""On-chip batch-scaling sweep for the device-sampling train step.

PERF.md establishes that the step at reference-recipe dims is
LATENCY-bound (~0.13 ms empty-scan floor, MFU ~1%). This sweep measures
the complement: where the batch-size curve leaves the latency corner
and what MFU/HBM utilization the design reaches when allowed to batch
up — the throughput-optimal operating point (the reference's recipes
fix batch at 512/1000 because its host sampler is the bottleneck;
reference examples/sage.py:80-98, sage_reddit.py:80-97 — on TPU the
sampler is on-device, so the operating point is free to move).

One JSON line per (config, batch) point with step wall ms, edges/s, and
the XLA cost-model roofline (MFU / HBM util) — appended to
.bench_bank/sweep.jsonl the moment each point completes (same banking
discipline as bench.py: a relay wedge mid-sweep keeps every completed
point). Each config runs in a killable subprocess; the parent never
touches the backend.

    python scripts/batch_sweep.py [--configs ppi,reddit]
        [--batches 512,2048,8192,32768]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_spec = importlib.util.spec_from_file_location(
    "bench_lib", os.path.join(_REPO, "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def sweep_config(name: str, batches, out_path: str) -> None:
    """All batch points for one config in this process (the graph build
    and feature-table upload are shared across points; each point's line
    is banked the moment it exists)."""
    import jax

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.datasets import build_synthetic
    from euler_tpu.models import SupervisedGraphSage

    def _bank_line(line: dict) -> None:
        with open(out_path, "a") as f:
            f.write(json.dumps(line) + "\n")
        print(json.dumps(line), flush=True)

    cfg = bench.CONFIGS[name]
    platform = jax.devices()[0].platform
    if cfg.get("powerlaw"):
        if platform == "cpu":
            # the 114M-edge graph at batch 32768 is a chip workload; on
            # a CPU fallback it would grind until the deadline SIGKILL
            # and bank a misleading "relay wedge?" error (checked BEFORE
            # the cache gate: a TPU-less machine must not be told to
            # build a ~2 GB cache for a sweep it would skip anyway)
            _bank_line({"config": name,
                        "note": "heavytail sweep skipped on CPU "
                        "(TPU-only)"})
            return
        # heavy-tail config sweeps only against a FINISHED cache (the
        # ~2 GB build must not burn a chip window; same gate as
        # tpu_checks)
        from euler_tpu.datasets import (
            REDDIT_HEAVYTAIL, heavytail_cache_dir, powerlaw_cache_ready,
        )

        cfg = {**cfg, **REDDIT_HEAVYTAIL}
        cache = heavytail_cache_dir()
        if not powerlaw_cache_ready(cache, **REDDIT_HEAVYTAIL):
            _bank_line({"config": name,
                        "error": "heavytail cache absent/stale; build "
                        "with scripts/reddit_heavytail.py --full first"})
            return
    else:
        cache = os.environ.get(
            "EULER_TPU_BENCH_CACHE", "/tmp/euler_tpu_bench"
        ) + "_" + cfg.get("cache_as", name)
        build_synthetic(
            cache,
            num_nodes=cfg["num_nodes"],
            avg_degree=cfg["avg_degree"],
            feature_dim=cfg["feature_dim"],
            label_dim=cfg["label_dim"],
            multilabel=cfg["multilabel"],
        )
    graph = euler_tpu.Graph(directory=cache)
    fanouts = list(cfg["fanouts"])
    edges_per_root = fanouts[0] + fanouts[0] * (
        fanouts[1] if len(fanouts) > 1 else 0
    )
    opt = train_lib.get_optimizer("adam", cfg["lr"])

    for batch in batches:
        point = {"config": name, "batch": int(batch),
                 "fanouts": fanouts, "dim": cfg["dim"],
                 "platform": platform}
        try:
            model = SupervisedGraphSage(
                label_idx=0,
                label_dim=cfg["label_dim"],
                metapath=[[0]] * len(fanouts),
                fanouts=fanouts,
                dim=cfg["dim"],
                feature_idx=1,
                feature_dim=cfg["feature_dim"],
                max_id=cfg["num_nodes"] - 1,
                device_features=True,
                device_sampling=True,
                feature_dtype=cfg.get("feature_dtype"),
            )
            if cfg.get("alias_sampling"):
                model.set_sampling_options(alias=True)
            state = model.init_state(
                jax.random.PRNGKey(0), graph,
                graph.sample_node(batch, -1), opt,
            )
            point["pallas_kernel"] = bench.detect_pallas_kernel(state)
            chunk_steps = 50
            scan = jax.jit(
                train_lib.make_scan_train(model, opt, chunk_steps, batch),
                donate_argnums=(0,),
            )
            state, l0 = scan(state, 0)  # compile + warmup
            jax.block_until_ready(l0)
            chunks = 2 if platform == "cpu" else 6
            t0 = time.perf_counter()
            last = None
            for c in range(1, chunks + 1):
                state, last = scan(state, c)
            jax.block_until_ready(last)
            dt = time.perf_counter() - t0
            step_ms = dt / (chunks * chunk_steps) * 1e3
            bogus = bench._implausible(step_ms, last)
            if bogus:
                point["error"] = f"measurement rejected: {bogus}"
            else:
                sps = chunks * chunk_steps / dt
                point["step_wall_ms"] = round(step_ms, 4)
                point["steps_per_sec"] = round(sps, 2)
                point["edges_per_sec"] = round(edges_per_root * batch * sps, 1)
                point["final_loss"] = round(
                    float(np.asarray(last)[-1]), 4
                )
                try:
                    point["roofline"] = bench._roofline(
                        scan.lower(state, 0).compile(), step_ms
                    )
                except Exception:
                    pass
            del state
            if (platform != "cpu" and point.get("pallas_kernel")
                    and "error" not in point):
                # per-point kernel A/B: does the fused draw still matter
                # off the latency corner? Shared helper (bench.kernel_ab)
                # so the env-toggle protocol cannot fork; the main state
                # is freed first — two full states resident would double
                # peak HBM at the big batch points.
                point.update(bench.kernel_ab(
                    model, opt, graph, batch, chunk_steps,
                    point["steps_per_sec"], chunks=2,
                ))
        except Exception as e:  # noqa: BLE001 — bank the failure, move on
            point["error"] = f"{type(e).__name__}: {e}"[:300]
        _bank_line(point)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--configs", default="ppi,reddit")
    ap.add_argument("--batches", default="512,2048,8192,32768")
    ap.add_argument("--out", default=os.path.join(
        _REPO, ".bench_bank", "sweep.jsonl"
    ))
    ap.add_argument("--run-one", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--platform", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-config subprocess deadline (s); x3 on CPU. "
                    "Default: per-config (900 s, +700 s on TPU for the "
                    "per-point kernel A/B's second init+compile; "
                    "reddit_heavytail 2400 s — one alias upload plus a "
                    "compile per batch point, no A/B on the alias path)")
    args = ap.parse_args()
    batches = [int(b) for b in args.batches.split(",") if b.strip()]
    os.makedirs(os.path.dirname(args.out), exist_ok=True)

    if args.run_one:
        if args.platform == "cpu":
            from euler_tpu.parallel import force_cpu_devices

            force_cpu_devices(1)
        else:
            from euler_tpu.parallel import honor_jax_platforms_env

            honor_jax_platforms_env()
        from euler_tpu.parallel import enable_compile_cache

        enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
        sweep_config(args.run_one, batches, args.out)
        return

    import signal
    import subprocess

    tpu_possible = os.environ.get(
        "JAX_PLATFORMS", ""
    ).split(",")[0].strip() in ("", "axon", "tpu")
    platform, err = (None, "JAX_PLATFORMS pins a non-TPU backend")
    if tpu_possible:
        platform, err = bench.probe_backend(3, 150.0, 20.0)
    child_platform = None if platform in ("tpu", "axon") else "cpu"
    if child_platform == "cpu":
        print(json.dumps({"note": f"CPU fallback: {err}"}), file=sys.stderr)
    # the heavytail sweep does strictly more than bench's single point
    # (same graph load + alias upload, then a compile per batch point);
    # on TPU, ppi/reddit points also each pay the kernel A/B's second
    # init_state + compile, hence the +700 below (CPU runs no A/B, and
    # neither does reddit_heavytail — its alias path skips the kernel
    # A/B, so it must not inherit A/B headroom its points never spend)
    caps = {"reddit_heavytail": 2400.0}
    no_ab = {"reddit_heavytail"}  # alias-path configs: no kernel A/B
    for name in [n.strip() for n in args.configs.split(",") if n.strip()]:
        ab_bump = (
            0.0 if (child_platform == "cpu" or name in no_ab) else 700.0
        )
        deadline = (
            args.deadline
            if args.deadline is not None
            else caps.get(name, 900.0) + ab_bump
        ) * (3.0 if child_platform == "cpu" else 1.0)
        cmd = [
            sys.executable, "-u", os.path.abspath(__file__),
            "--run-one", name, "--batches", args.batches,
            "--out", args.out,
        ]
        if child_platform:
            cmd += ["--platform", child_platform]
        proc = subprocess.Popen(cmd, start_new_session=True)
        try:
            proc.wait(timeout=deadline)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            proc.wait()
            print(json.dumps({
                "config": name,
                "error": f"sweep subprocess killed at {deadline:.0f}s "
                "(relay wedge?); completed points are banked",
            }), flush=True)


if __name__ == "__main__":
    main()
