#!/usr/bin/env python3
"""Export a merged Chrome-trace/Perfetto JSON from a live euler_tpu
cluster (and/or an existing run trace).

`run_loop --trace_file=` already writes the full merged trace for a
training run it owns. This is the standalone tool for everything else:

  * scrape a LIVE cluster's slow-span journals (server side of every
    shard) into a trace you can open in ui.perfetto.dev;
  * `--input run_trace.json` merges a trace written earlier by
    run_loop (phase slices + the client journal) with a fresh scrape —
    e.g. to re-pull shard journals after the training client exited;
  * `--smoke` spins a tiny in-process 2-shard cluster, drives traffic
    through an instrumented prefetch loop, exports, and asserts the
    result is valid Chrome-trace JSON whose client/server slow-span
    slices share wire-v3 trace ids (the verify.sh gate).

Each process lands on its own pid lane (train = 1, shard s = 100+s);
client-call -> server-handler arrows are flow events keyed by the
wire-v3 trace id, so a slow step is followable from the consumer stall
to the exact shard handler regardless of clock skew.

Usage:
    python scripts/trace_dump.py --registry /shared/reg --out t.json
    python scripts/trace_dump.py --shards h1:9001,h2:9001 --out t.json
    python scripts/trace_dump.py --input run_trace.json \\
        --registry /shared/reg --out merged.json
    python scripts/trace_dump.py --smoke

See OBSERVABILITY.md "Step phases" for the triage runbook.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def export(graph=None, base_events=None, include_local=False) -> dict:
    """Build the merged trace dict: optional pre-existing events +
    (optionally) this process's journal + every reachable shard's."""
    from euler_tpu import trace as TR

    sources = []
    if include_local:
        sources = TR.gather_span_sources(None)
    if graph is not None:
        from euler_tpu import telemetry as T

        for s in range(graph.num_shards):
            try:
                sources.append(
                    (T.scrape(graph, s), TR.PID_SHARD_BASE + s,
                     f"shard {s}")
                )
            except Exception as e:
                print(f"shard {s}: scrape failed ({e}); skipped",
                      file=sys.stderr)
    return TR.chrome_trace(None, sources, base_events)


def run_smoke() -> int:
    """Self-contained export check (the verify.sh gate): tiny 2-shard
    cluster, instrumented prefetch traffic with a seeded handler stall
    so slow spans exist on both sides, then assert the merged trace is
    valid and correlated."""
    import shutil
    import tempfile

    import euler_tpu
    from euler_tpu import telemetry as T
    from euler_tpu import trace as TR
    from euler_tpu.graph import native
    from euler_tpu.graph.service import GraphService
    from euler_tpu.parallel import prefetch
    from scripts.remote_bench import build_powerlaw_fixture

    tmp = tempfile.mkdtemp(prefix="euler_trace_smoke_")
    svcs = []
    try:
        data = os.path.join(tmp, "data")
        os.makedirs(data)
        build_powerlaw_fixture(data, 120, 6, 8)
        svcs = [GraphService(data, s, 2) for s in range(2)]
        g = euler_tpu.Graph(
            mode="remote", shards=[s.address for s in svcs],
            retries=2, timeout_ms=5000,
        )
        try:
            T.telemetry_reset()
            recorder = TR.TraceRecorder().start()
            # a seeded 5 ms handler stall guarantees both journals hold
            # the SAME requests (slow enough to beat the journal floor)
            native.fault_config("handler_stall:delay@5", 11)
            try:
                def make_batch(step):
                    roots = g.sample_node(8, -1)
                    g.get_dense_feature(roots, [0], [8])
                    return roots

                for _ in prefetch(make_batch, 4, depth=2, num_threads=2):
                    pass
            finally:
                native.fault_clear()
                recorder.stop()
            out = os.path.join(tmp, "trace.json")
            trace = TR.write_trace(out, recorder, g)
            with open(out) as f:
                reread = json.load(f)
            events = TR.validate_chrome_trace(reread)
            phases = {e["name"] for e in events
                      if e.get("cat") == "phase"}
            assert {"input_stall", "sample"} <= phases, phases
            correlated = TR.correlated_trace_ids(reread)
            assert correlated, "no client/server trace-id pair in the " \
                "merged trace"
            pids = {e["pid"] for e in events}
            assert {TR.PID_TRAIN, TR.PID_SHARD_BASE,
                    TR.PID_SHARD_BASE + 1} <= pids, pids
            print(f"trace_dump smoke: OK ({len(events)} events, "
                  f"{len(correlated)} correlated trace ids)")
            return 0
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--registry", default="", help=(
        "registry dir or tcp://host:port the cluster registered with"))
    ap.add_argument("--shards", default="", help=(
        "explicit comma-separated host:port shard list"))
    ap.add_argument("--input", default="", help=(
        "existing Chrome-trace JSON (e.g. run_loop --trace_file output) "
        "to merge the scraped spans into"))
    ap.add_argument("--out", default="", help=(
        "output path (default: stdout)"))
    ap.add_argument("--timeout_ms", type=int, default=3000)
    ap.add_argument("--smoke", action="store_true", help=(
        "self-contained export check against a tiny in-process "
        "cluster (the verify.sh gate)"))
    args = ap.parse_args()

    if args.smoke:
        return run_smoke()
    if not (args.registry or args.shards or args.input):
        ap.error("need --registry, --shards, or --input (or --smoke)")

    base_events = None
    if args.input:
        from euler_tpu.trace import validate_chrome_trace

        with open(args.input) as f:
            base_events = validate_chrome_trace(json.load(f))

    g = None
    if args.registry or args.shards:
        import euler_tpu

        g = euler_tpu.Graph(
            mode="remote",
            registry=args.registry or None,
            shards=args.shards.split(",") if args.shards else None,
            retries=2,
            timeout_ms=args.timeout_ms,
            rediscover_ms=0,
        )
    try:
        trace = export(g, base_events)
    finally:
        if g is not None:
            g.close()
    text = json.dumps(trace)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"{len(trace['traceEvents'])} events -> {args.out} "
              "(open in ui.perfetto.dev)", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
