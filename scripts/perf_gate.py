#!/usr/bin/env python3
"""Performance regression gate over the bench trajectory.

Two jobs (ISSUE 6 satellite; see PERF.md "Throughput trajectory"):

1. **Trajectory** — parse the driver-recorded BENCH_r0*.json rounds
   into one table (round, headline edges/s, platform) so the repo's
   throughput history is a first-class artifact instead of five JSON
   blobs (`--table` prints it as markdown for PERF.md).

2. **Regression verdict** — run the cheap smoke benches
   (`bench.py --smoke`, `scripts/remote_bench.py --smoke`), compare
   each against the BEST prior smoke round recorded in
   ``evidence/perf_gate/history.jsonl``, and print a verdict. Smoke
   numbers are NOT comparable to the full-config BENCH trajectory
   (different graph sizes), which is why the gate keeps its own
   smoke-to-smoke history; every run appends to it.

Warn-only by default — verify.sh calls it so a silent throughput
regression is at least SHOUTED before it reaches a PR — `--strict`
exits nonzero on a regression beyond ``--tolerance`` (default 25%,
sized for this container's run-to-run noise; see PERF.md's
measurement-noise notes).

Usage:
    python scripts/perf_gate.py                 # run smokes + verdict
    python scripts/perf_gate.py --strict        # same, exit 1 on regress
    python scripts/perf_gate.py --table         # trajectory markdown only
    python scripts/perf_gate.py --skip-bench    # remote smoke only
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HISTORY = os.path.join(REPO, "evidence", "perf_gate", "history.jsonl")


def load_trajectory(repo: str = REPO) -> list:
    """BENCH_r0*.json -> [{round, value, unit, metric, platform}],
    rounds with no parsed headline (a failed bench run) included with
    value None so the table shows the gap honestly."""
    rows = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except ValueError:
            continue
        p = d.get("parsed") or {}
        rows.append({
            "round": d.get("n"),
            "value": p.get("value"),
            "unit": p.get("unit"),
            "metric": p.get("metric"),
            "platform": (p.get("detail") or {}).get("platform"),
            "error": (p.get("error") or "")[:60] or None,
        })
    return rows


def trajectory_markdown(rows: list) -> str:
    out = ["| round | headline edges/s | platform | note |",
           "|---|---|---|---|"]
    best = max((r["value"] for r in rows if r["value"]), default=None)
    for r in rows:
        val = f"{r['value']:,.0f}" if r["value"] else "—"
        if r["value"] and r["value"] == best:
            val = f"**{val}**"
        note = r["error"] or ""
        out.append(f"| {r['round']} | {val} | {r['platform'] or '—'} "
                   f"| {note} |")
    return "\n".join(out)


def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def run_smoke_bench(timeout_s: float) -> dict | None:
    """bench.py --smoke headline (tiny host-path-only config)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        print(f"perf_gate: bench.py --smoke timed out ({timeout_s}s)",
              file=sys.stderr)
        return None
    return _last_json_line(proc.stdout)


def run_smoke_remote(timeout_s: float) -> dict | None:
    """remote_bench --smoke headline (2-shard remote client path)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "remote_bench.py"), "--smoke"],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        print(f"perf_gate: remote_bench --smoke timed out ({timeout_s}s)",
              file=sys.stderr)
        return None
    return _last_json_line(proc.stdout)


def load_history(path: str = HISTORY) -> list:
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass
    return rows


def append_history(record: dict, path: str = HISTORY) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def verdict(current: dict, history: list, tolerance: float) -> list:
    """Compare {config: edges/s} against the best prior history entry
    per config. Returns [(config, status, detail)], status in
    {"ok", "regression", "baseline"}."""
    out = []
    for config, value in sorted(current.items()):
        if value is None:
            out.append((config, "baseline",
                        "smoke run failed; nothing recorded"))
            continue
        prior = [h["values"].get(config) for h in history
                 if h.get("values", {}).get(config)]
        if not prior:
            out.append((config, "baseline",
                        f"{value:,.0f} edges/s (first smoke round — "
                        "baseline recorded)"))
            continue
        best = max(prior)
        floor = best * (1.0 - tolerance)
        ratio = value / best
        detail = (f"{value:,.0f} edges/s vs best prior {best:,.0f} "
                  f"({ratio:.2f}x, floor {floor:,.0f})")
        out.append((config,
                    "regression" if value < floor else "ok", detail))
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on a regression verdict (default: warn)")
    ap.add_argument("--strict-configs", default="", metavar="A,B", help=(
        "enforce (exit 1) regressions only for these config names "
        "(e.g. bench_smoke); others stay warn-only. The verify.sh "
        "middle ground: the host-only bench config is stable enough "
        "to gate on, the remote configs drown in 1-core container "
        "noise"))
    ap.add_argument("--tolerance", type=float, default=0.25, help=(
        "allowed fractional drop below the best prior smoke round "
        "before the verdict says regression (container noise floor)"))
    ap.add_argument("--table", action="store_true",
                    help="print the BENCH trajectory markdown and exit")
    ap.add_argument("--skip-bench", action="store_true",
                    help="skip bench.py --smoke (remote smoke only)")
    ap.add_argument("--skip-remote", action="store_true",
                    help="skip remote_bench --smoke")
    ap.add_argument("--timeout", type=float, default=420.0,
                    help="per-smoke subprocess timeout, seconds")
    ap.add_argument("--no-record", action="store_true",
                    help="don't append this run to the smoke history")
    ap.add_argument("--history", default=HISTORY, help=argparse.SUPPRESS)
    args = ap.parse_args()

    rows = load_trajectory()
    if args.table:
        print(trajectory_markdown(rows))
        return 0

    print("== bench trajectory (BENCH_r0*.json, full configs) ==")
    for r in rows:
        val = f"{r['value']:,.0f}" if r["value"] else "(no headline)"
        print(f"  round {r['round']}: {val} {r['unit'] or ''} "
              f"[{r['platform'] or '?'}]")

    current: dict = {}
    if not args.skip_bench:
        head = run_smoke_bench(args.timeout)
        current["bench_smoke"] = head.get("value") if head else None
    if not args.skip_remote:
        head = run_smoke_remote(args.timeout)
        current["remote_smoke"] = head.get("value") if head else None
    if not current:
        print("perf_gate: both smokes skipped; trajectory only")
        return 0

    history = load_history(args.history)
    results = verdict(current, history, args.tolerance)
    if not args.no_record and any(v for v in current.values()):
        append_history(
            {"unix": int(time.time()),
             "values": {k: v for k, v in current.items() if v}},
            args.history,
        )

    strict_configs = {c.strip() for c in args.strict_configs.split(",")
                      if c.strip()}
    print("== perf gate verdict (smoke-to-smoke, "
          f"tolerance {args.tolerance:.0%}) ==")
    regressed = False
    enforced = False
    for config, status, detail in results:
        tag = {"ok": "OK", "regression": "REGRESSION",
               "baseline": "BASELINE"}[status]
        gating = args.strict or config in strict_configs
        print(f"  {config:14s} {tag:10s} {detail}"
              + ("" if gating or status != "regression"
                 else " [warn-only config]"))
        regressed |= status == "regression"
        enforced |= status == "regression" and gating
    if regressed:
        print("perf_gate: REGRESSION "
              + ("(enforced: failing)" if enforced
                 else "(warn-only; pass --strict or --strict-configs "
                      "to enforce)"))
        return 1 if enforced else 0
    print("perf_gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
